"""KFServingClient: the programmatic SDK for the serving fabric.

Mirrors the reference Python SDK's surface (reference
python/kfserving/kfserving/api/kf_serving_client.py:29-380 —
create/get/patch/delete/wait_isvc_ready plus TrainedModel ops, and
kf_serving_watch.py's watch loop) against the TPU control API and
ingress router instead of the K8s apiserver:

    client = KFServingClient("http://127.0.0.1:8081",
                             "http://127.0.0.1:8080")
    await client.create(isvc_dict)
    await client.wait_isvc_ready("sklearn-iris")
    result = await client.predict("sklearn-iris",
                                  {"instances": [[6.8, 2.8, 4.8, 1.4]]})

All methods are async (the whole stack is asyncio); use
``asyncio.run(...)`` from synchronous code or the CLI
(`python -m kfserving_tpu.client`).
"""

import asyncio
import json
from dataclasses import asdict, is_dataclass
from urllib.parse import quote
from typing import Any, Dict, List, Optional

from kfserving_tpu.reliability import RetryPolicy, fault_sites, faults

DEFAULT_TIMEOUT_S = 60.0


class ClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class TimeoutError_(Exception):
    pass


def _to_dict(obj: Any) -> Dict[str, Any]:
    if is_dataclass(obj) and not isinstance(obj, type):
        return asdict(obj)
    if isinstance(obj, dict):
        return obj
    raise TypeError(f"expected spec dict or dataclass, got {type(obj)}")


class KFServingClient:
    """Async client for the control API (+ optional ingress data plane)."""

    def __init__(self, control_url: str,
                 ingress_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 retry: Optional[RetryPolicy] = None):
        self.control_url = control_url.rstrip("/")
        self.ingress_url = (ingress_url or "").rstrip("/") or None
        self.timeout_s = timeout_s
        self._session = None
        # Connection-level retry (KFS_CLIENT_RETRY_* knobs): a refused
        # or unroutable connect means the request never reached the
        # server, so replay is safe for every verb — including the
        # non-idempotent ones.  Errors AFTER dispatch (HTTP statuses,
        # mid-body resets, timeouts) are never retried here: a replayed
        # POST could double-create or double-infer.  Built lazily when
        # not supplied (the retryable-class tuple needs aiohttp).
        self._retry = retry

    @property
    def retry(self) -> RetryPolicy:
        if self._retry is None:
            import aiohttp

            from kfserving_tpu.reliability import FaultInjected

            self._retry = RetryPolicy.from_env(
                "KFS_CLIENT",
                retry_on=(aiohttp.ClientConnectorError,
                          ConnectionRefusedError, FaultInjected))
        return self._retry

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def __aenter__(self):
        await self._ensure_session()
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def _request(self, method: str, url: str,
                       body: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        session = await self._ensure_session()
        data = json.dumps(body).encode() if body is not None else None

        async def attempt():
            await faults.inject(fault_sites.CLIENT_REQUEST, key=url)
            async with session.request(method, url, data=data) as resp:
                payload = await resp.read()
                try:
                    decoded = json.loads(payload) if payload else {}
                except ValueError:
                    decoded = {"raw": payload.decode("utf-8", "replace")}
                if resp.status >= 400:
                    raise ClientError(
                        resp.status,
                        decoded.get("error", decoded.get("raw", "")))
                return decoded

        # Only pre-dispatch connection errors are classified retryable
        # (see __init__); ClientError carries the server's verdict and
        # is final.
        return await self.retry.acall(attempt)

    # -- InferenceService CRUD (reference kf_serving_client.py:89-231) ------
    async def create(self, isvc: Any) -> Dict[str, Any]:
        return await self._request(
            "POST", f"{self.control_url}/v1/inferenceservices",
            _to_dict(isvc))

    async def get(self, name: Optional[str] = None,
                  namespace: str = "default") -> Dict[str, Any]:
        if name is None:
            return await self._request(
                "GET", f"{self.control_url}/v1/inferenceservices")
        return await self._request(
            "GET",
            f"{self.control_url}/v1/inferenceservices/{namespace}/{name}")

    async def patch(self, name: str, patch: Dict[str, Any],
                    namespace: str = "default") -> Dict[str, Any]:
        return await self._request(
            "PATCH",
            f"{self.control_url}/v1/inferenceservices/{namespace}/{name}",
            patch)

    async def delete(self, name: str, namespace: str = "default"
                     ) -> Dict[str, Any]:
        return await self._request(
            "DELETE",
            f"{self.control_url}/v1/inferenceservices/{namespace}/{name}")

    # -- rollout helpers (reference canary docs flow) -----------------------
    async def rollout_canary(self, name: str, percent: int,
                             namespace: str = "default",
                             **spec_changes) -> Dict[str, Any]:
        """Set canary traffic percent (optionally with spec changes that
        mint the new revision)."""
        patch: Dict[str, Any] = {"predictor": {
            "canary_traffic_percent": percent, **spec_changes}}
        return await self.patch(name, patch, namespace)

    async def promote(self, name: str, namespace: str = "default"
                      ) -> Dict[str, Any]:
        """Promote the canary to 100% (clears the split; the losing
        revision is garbage-collected)."""
        return await self.patch(
            name, {"predictor": {"canary_traffic_percent": None}},
            namespace)

    async def rollouts(self) -> Dict[str, Any]:
        """Progressive-delivery status from the ingress router:
        active rollouts, recent promotions/rollbacks (with pinned
        evidence), and the quarantine ledger."""
        return await self._request("GET",
                                   f"{self._ingress()}/v2/rollouts")

    async def profile(self, window_s: Optional[float] = None,
                      replica: Optional[str] = None,
                      fmt: str = "trace_json") -> Dict[str, Any]:
        """Fetch the fleet's device-time profile from the ingress
        router: the engine event timeline (decode waves, prefill
        chunks, preemptions, HOLD windows) as Chrome-trace JSON ready
        for Perfetto (fmt="events" returns raw per-replica event
        lists instead)."""
        params = [f"format={fmt}"]
        if window_s is not None:
            params.append(f"window_s={float(window_s)}")
        if replica:
            params.append(f"replica={replica}")
        qs = "&".join(params)
        return await self._request(
            "GET", f"{self._ingress()}/debug/profile?{qs}")

    async def cache(self, replica: Optional[str] = None,
                    top_k: Optional[int] = None,
                    top_cost: Optional[int] = None) -> Dict[str, Any]:
        """Fetch the fleet's federated cache snapshot from the ingress
        router: per-replica prefix-index census (entry count,
        reuse-depth distribution, top-K hot chains), block-pool
        occupancy, and HBM residency — the observability feed
        prefix-affinity routing consumes.  `replica` narrows to one
        host; `top_k` bounds the hot-chain list; `top_cost` adds the
        top-N cost-attribution records ranked by attributed device-ms
        and by KV blocks held (ISSUE 18)."""
        params = []
        if replica:
            params.append(f"replica={replica}")
        if top_k is not None:
            params.append(f"top_k={int(top_k)}")
        if top_cost is not None:
            params.append(f"top_cost={int(top_cost)}")
        qs = ("?" + "&".join(params)) if params else ""
        return await self._request(
            "GET", f"{self._ingress()}/debug/cache{qs}")

    async def incidents(self, incident_id: Optional[str] = None,
                        state: Optional[str] = None,
                        limit: Optional[int] = None,
                        replica: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Fetch diagnosed incidents from the ingress router: each
        replica's incident summaries under its host key plus the
        fleet rollup deduplicated by (root cause, model) and the
        router's own admission/brownout state.  `incident_id` pulls
        one full evidence-bearing record from whichever replica owns
        it; `state` filters (\"open\"/\"closed\"); `replica` narrows
        to one host."""
        params = []
        if incident_id:
            params.append(f"id={quote(incident_id)}")
        if state:
            params.append(f"state={quote(state)}")
        if limit is not None:
            params.append(f"limit={int(limit)}")
        if replica:
            params.append(f"replica={replica}")
        qs = ("?" + "&".join(params)) if params else ""
        return await self._request(
            "GET", f"{self._ingress()}/debug/incidents{qs}")

    async def history(self, series: Optional[str] = None,
                      labels: Optional[Dict[str, str]] = None,
                      window_s: Optional[float] = None,
                      step_s: Optional[float] = None,
                      replica: Optional[str] = None) -> Dict[str, Any]:
        """Fetch federated telemetry history from the ingress router:
        each replica's ring-TSDB frames for `series` (a family name;
        None = every live series) under its `replica` key, plus the
        fleet rollup merged by grid timestamp (rates sum, gauges/
        quantiles/ratios mean).  `labels` filters by label subset,
        `window_s` bounds the lookback, `step_s` overrides the 1 s
        alignment grid, `replica` narrows to one host."""
        params = []
        if series:
            params.append(f"series={quote(series)}")
        if labels:
            pairs = ",".join(f"{k}={v}" for k, v in
                             sorted(labels.items()))
            params.append(f"labels={quote(pairs)}")
        if window_s is not None:
            params.append(f"window_s={float(window_s)}")
        if step_s is not None:
            params.append(f"step_s={float(step_s)}")
        if replica:
            params.append(f"replica={replica}")
        qs = ("?" + "&".join(params)) if params else ""
        return await self._request(
            "GET", f"{self._ingress()}/debug/history{qs}")

    # -- readiness (reference wait_isvc_ready, kf_serving_client.py:232+) ---
    async def wait_isvc_ready(self, name: str, namespace: str = "default",
                              timeout_seconds: float = 120.0,
                              polling_interval: float = 0.2) -> None:
        deadline = asyncio.get_event_loop().time() + timeout_seconds
        last: Dict[str, Any] = {}
        while asyncio.get_event_loop().time() < deadline:
            try:
                last = await self.get(name, namespace)
            except ClientError as e:
                if e.status != 404:
                    raise
                last = {}
            status = (last or {}).get("status") or {}
            if status.get("ready"):
                return
            await asyncio.sleep(polling_interval)
        raise TimeoutError_(
            f"timeout waiting for {namespace}/{name} ready; "
            f"last status: {json.dumps((last or {}).get('status'))}")

    # -- TrainedModel ops (reference client TrainedModel section) -----------
    async def create_trained_model(self, tm: Any) -> Dict[str, Any]:
        return await self._request(
            "POST", f"{self.control_url}/v1/trainedmodels", _to_dict(tm))

    async def get_trained_model(self, name: Optional[str] = None,
                                namespace: str = "default"
                                ) -> Dict[str, Any]:
        if name is None:
            return await self._request(
                "GET", f"{self.control_url}/v1/trainedmodels")
        return await self._request(
            "GET", f"{self.control_url}/v1/trainedmodels/{namespace}/{name}")

    async def delete_trained_model(self, name: str,
                                   namespace: str = "default"
                                   ) -> Dict[str, Any]:
        return await self._request(
            "DELETE",
            f"{self.control_url}/v1/trainedmodels/{namespace}/{name}")

    # -- data plane ---------------------------------------------------------
    def _ingress(self) -> str:
        if self.ingress_url is None:
            raise ValueError(
                "no ingress_url configured; pass it to KFServingClient "
                "to use predict/explain")
        return self.ingress_url

    async def predict(self, name: str, payload: Dict[str, Any],
                      protocol: str = "v1",
                      model_name: Optional[str] = None) -> Dict[str, Any]:
        """POST a predict request through the ingress router.

        model_name: path model (defaults to the isvc name; differs for
        TrainedModels served under a parent isvc)."""
        model = model_name or name
        if protocol == "v2":
            url = f"{self._ingress()}/v2/models/{model}/infer"
        else:
            url = f"{self._ingress()}/v1/models/{model}:predict"
        return await self._request("POST", url, payload)

    async def predict_binary(self, name: str, tensors: Dict[str, Any],
                             model_name: Optional[str] = None,
                             binary_output: bool = False
                             ) -> Dict[str, Any]:
        """V2 binary-wire predict: tensors {name: ndarray} ship as raw
        bytes (Inference-Header-Content-Length extension) — the fast
        wire for dense inputs (images, token ids).  binary_output=True
        returns outputs as raw bytes too; their "data" decode to numpy
        arrays client-side."""
        import numpy as np

        from kfserving_tpu.protocol import v2 as v2proto

        model = model_name or name
        body, hlen = v2proto.make_binary_request(
            {k: np.asarray(v) for k, v in tensors.items()},
            binary_output=binary_output)
        url = f"{self._ingress()}/v2/models/{model}/infer"
        session = await self._ensure_session()
        headers = {"Inference-Header-Content-Length": str(hlen),
                   "Content-Type": "application/octet-stream"}
        async with session.post(url, data=body, headers=headers) as resp:
            payload = await resp.read()
            resp_hlen = resp.headers.get(
                "Inference-Header-Content-Length")
            if resp.status < 400 and resp_hlen:
                return v2proto.decode_binary_response(
                    payload, int(resp_hlen))
            try:
                decoded = json.loads(payload) if payload else {}
            except ValueError:
                decoded = {"raw": payload.decode("utf-8", "replace")}
            if resp.status >= 400:
                raise ClientError(
                    resp.status,
                    decoded.get("error", decoded.get("raw", "")))
            return decoded

    async def explain(self, name: str, payload: Dict[str, Any],
                      model_name: Optional[str] = None) -> Dict[str, Any]:
        model = model_name or name
        url = f"{self._ingress()}/v1/models/{model}:explain"
        return await self._request("POST", url, payload)

    # -- credential registration (reference api/creds_utils.py:26-142) ------
    async def create_secret(self, payload: Dict[str, Any],
                            service_account: Optional[str] = None,
                            name: Optional[str] = None) -> str:
        """Register a secret; returns the (possibly generated) name."""
        body = dict(payload)
        if name:
            body["name"] = name
        if service_account:
            body["serviceAccount"] = service_account
        result = await self._request(
            "POST", f"{self.control_url}/v1/secrets", body)
        return result["name"]

    async def attach_secret(self, service_account: str,
                            secret_name: str) -> Dict[str, Any]:
        return await self._request(
            "POST",
            f"{self.control_url}/v1/serviceaccounts/{service_account}"
            f"/secrets",
            {"secret": secret_name})

    async def list_secrets(self) -> Dict[str, Any]:
        return await self._request(
            "GET", f"{self.control_url}/v1/secrets")

    async def delete_secret(self, name: str) -> Dict[str, Any]:
        return await self._request(
            "DELETE", f"{self.control_url}/v1/secrets/{name}")

    async def set_gcs_credentials(self, credentials_file: str,
                                  service_account: str = "default") -> str:
        """Register a GCS key file (reference set_gcs_credentials)."""
        from kfserving_tpu.client.creds import gcs_secret_payload

        # Executor read (kfslint async-blocking): the SDK runs inside
        # callers' live event loops, and a key file can sit on a slow
        # mount.
        payload = await asyncio.get_running_loop().run_in_executor(
            None, gcs_secret_payload, credentials_file)
        return await self.create_secret(
            payload, service_account=service_account)

    async def set_s3_credentials(self, credentials_file: str,
                                 service_account: str = "default",
                                 s3_profile: str = "default",
                                 s3_endpoint: Optional[str] = None,
                                 s3_region: Optional[str] = None,
                                 s3_use_https: Optional[str] = None,
                                 s3_verify_ssl: Optional[str] = None
                                 ) -> str:
        """Register AWS-CLI-format credentials (reference
        set_s3_credentials; endpoint/region/SSL knobs become the same
        secret annotations the builder consumes)."""
        from functools import partial

        from kfserving_tpu.client.creds import s3_secret_payload

        payload = await asyncio.get_running_loop().run_in_executor(
            None, partial(s3_secret_payload, credentials_file,
                          s3_profile=s3_profile,
                          s3_endpoint=s3_endpoint,
                          s3_region=s3_region,
                          s3_use_https=s3_use_https,
                          s3_verify_ssl=s3_verify_ssl))
        return await self.create_secret(
            payload, service_account=service_account)

    async def set_azure_credentials(self, credentials_file: str,
                                    service_account: str = "default"
                                    ) -> str:
        """Register an Azure service-principal JSON (reference
        set_azure_credentials)."""
        from kfserving_tpu.client.creds import azure_secret_payload

        payload = await asyncio.get_running_loop().run_in_executor(
            None, azure_secret_payload, credentials_file)
        return await self.create_secret(
            payload, service_account=service_account)


def isvc_spec(name: str, framework: str, storage_uri: str,
              namespace: str = "default", **predictor_kwargs
              ) -> Dict[str, Any]:
    """Convenience builder for a minimal InferenceService spec dict
    (the SDK-side constructors the reference generates from swagger)."""
    return {
        "name": name,
        "namespace": namespace,
        "predictor": {
            "framework": framework,
            "storage_uri": storage_uri,
            **predictor_kwargs,
        },
    }
