"""HBM accounting and eviction for multi-model serving.

The reference's multi-model story is disk-based: the agent puller downloads
artifacts and POSTs load/unload to the server (reference pkg/agent/
puller.go:120-183), and the shard strategy is a stub that always returns
shard 0 (reference pkg/controller/v1alpha1/trainedmodel/sharding/memory/
strategy.go:29-39) with a declared-memory field on the TrainedModel spec
(reference pkg/apis/serving/v1alpha1/trained_model.go:68-69).

On TPU "loaded" means *resident in HBM*, which is the scarce resource.  This
module makes the Memory field real (SURVEY.md §7 hard parts): an accountant
tracks declared/measured bytes per model against the device budget, and an
LRU policy picks eviction victims when a load would overflow.
"""

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger("kfserving_tpu.hbm")


def device_hbm_bytes(device=None) -> Optional[int]:
    """Total HBM of the serving device, when the backend reports it."""
    import jax

    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        return stats.get("bytes_limit")
    return None


def device_hbm_in_use(device=None) -> Optional[int]:
    import jax

    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        return stats.get("bytes_in_use")
    return None


class InsufficientHBM(Exception):
    pass


@dataclass
class Residency:
    name: str
    bytes: int
    loaded_at: float
    last_used: float


class HBMManager:
    """Bin-packing accountant for model residency on one device/mesh.

    budget_bytes: capacity to pack into (defaults to 90% of reported HBM, or
    a conservative 12 GiB if the backend doesn't report — v5e has 16 GiB).
    evict_cb: called with a model name when the manager decides to evict; the
    callback must actually free the model (engine.close()).
    """

    DEFAULT_BUDGET = 12 * 1024**3

    def __init__(self, budget_bytes: Optional[int] = None,
                 evict_cb: Optional[Callable[[str], None]] = None,
                 headroom: float = 0.10):
        if budget_bytes is None:
            total = device_hbm_bytes()
            budget_bytes = (int(total * (1 - headroom)) if total
                            else self.DEFAULT_BUDGET)
        self.budget_bytes = budget_bytes
        self.evict_cb = evict_cb
        self._resident: "OrderedDict[str, Residency]" = OrderedDict()
        self._lock = threading.Lock()
        obs.hbm_budget_bytes().set(float(budget_bytes))

    @property
    def used_bytes(self) -> int:
        return sum(r.bytes for r in self._resident.values())

    @property
    def free_bytes(self) -> int:
        return self.budget_bytes - self.used_bytes

    def resident_models(self) -> List[str]:
        return list(self._resident.keys())

    def can_fit(self, nbytes: int) -> bool:
        return nbytes <= self.free_bytes

    def admit(self, name: str, nbytes: int, evict: bool = True) -> List[str]:
        """Account for a model of `nbytes` being loaded.

        Returns the list of models evicted to make room.  Raises
        InsufficientHBM if the model can never fit (bigger than budget) or
        eviction is disabled and there is no room.
        """
        with self._lock:
            if nbytes > self.budget_bytes:
                raise InsufficientHBM(
                    f"model {name} needs {nbytes} bytes; budget is "
                    f"{self.budget_bytes}")
            # Plan admission against a scratch copy so a failed admit leaves
            # the books untouched (nothing is physically evicted until the
            # plan commits — evict_cb runs only on success).  A reload of
            # `name` replaces its old entry rather than double-counting it.
            plan = OrderedDict(
                (k, v) for k, v in self._resident.items() if k != name)
            victims: List[str] = []
            while True:
                plan_free = self.budget_bytes - sum(
                    r.bytes for r in plan.values())
                if nbytes <= plan_free:
                    break
                if not evict:
                    raise InsufficientHBM(
                        f"model {name} needs {nbytes} bytes; only "
                        f"{plan_free} free and eviction disabled")
                victim = next(iter(plan), None)  # LRU order
                if victim is None:
                    raise InsufficientHBM(
                        f"model {name} needs {nbytes} bytes; nothing "
                        f"left to evict")
                plan.pop(victim)
                victims.append(victim)
            now = time.time()
            plan[name] = Residency(name, nbytes, now, now)
            self._resident = plan
        for victim in victims:
            logger.info("evicting model %s to fit %s", victim, name)
            obs.hbm_evictions_total().labels(model=victim).inc()
            obs.hbm_resident_bytes().prune(model=victim)
            if self.evict_cb:
                self.evict_cb(victim)
        obs.hbm_resident_bytes().labels(model=name).set(float(nbytes))
        return victims

    def touch(self, name: str) -> None:
        """Mark a model as recently used (moves it to MRU position)."""
        with self._lock:
            res = self._resident.get(name)
            if res is not None:
                res.last_used = time.time()
                self._resident.move_to_end(name)

    def release(self, name: str) -> None:
        with self._lock:
            self._resident.pop(name, None)
        # Prune, not zero: a released model must drop OUT of /metrics
        # (a forever-0 series per unloaded model would grow the scrape
        # unboundedly under multi-model churn).
        obs.hbm_resident_bytes().prune(model=name)

    def commit(self, staging: str, name: str,
               nbytes: Optional[int] = None) -> None:
        """Atomically replace ``name``'s entry with the ``staging`` entry.

        Used by zero-downtime reload: releasing old+staging and re-admitting
        would open a window where a concurrent admit claims the freed bytes
        and the re-admit fails after the new engine is already serving.
        Under the manager lock there is no such window.  ``nbytes``
        overrides the staged estimate with the measured size.
        """
        with self._lock:
            staged = self._resident.pop(staging, None)
            old = self._resident.pop(name, None)
            src = staged or old
            if src is None:
                return
            final = nbytes if nbytes is not None else src.bytes
            self._resident[name] = Residency(
                name, final, src.loaded_at, time.time())
        obs.hbm_resident_bytes().prune(model=staging)
        obs.hbm_resident_bytes().labels(model=name).set(float(final))

    def stats(self) -> Dict[str, float]:
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": self.used_bytes,
            "free_bytes": self.free_bytes,
            "resident_models": len(self._resident),
        }

    def debug(self) -> Dict[str, Any]:
        """The `/debug/cache` HBM snapshot: budget totals plus the
        per-model residency ledger in LRU order (index 0 = next
        eviction victim) — what the multi-model residency manager
        (ROADMAP item 4) will consume."""
        with self._lock:
            residents = [
                {"model": r.name, "bytes": r.bytes,
                 "loaded_at": round(r.loaded_at, 3),
                 "last_used": round(r.last_used, 3)}
                for r in self._resident.values()]
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": sum(r["bytes"] for r in residents),
            "resident": residents,
        }
