"""HBM accounting and eviction for multi-model serving.

The reference's multi-model story is disk-based: the agent puller downloads
artifacts and POSTs load/unload to the server (reference pkg/agent/
puller.go:120-183), and the shard strategy is a stub that always returns
shard 0 (reference pkg/controller/v1alpha1/trainedmodel/sharding/memory/
strategy.go:29-39) with a declared-memory field on the TrainedModel spec
(reference pkg/apis/serving/v1alpha1/trained_model.go:68-69).

On TPU "loaded" means *resident in HBM*, which is the scarce resource.  This
module makes the Memory field real (SURVEY.md §7 hard parts): an accountant
tracks declared/measured bytes per model against the device budget, and an
LRU policy picks eviction victims when a load would overflow.
"""

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger("kfserving_tpu.hbm")


def device_hbm_bytes(device=None) -> Optional[int]:
    """Total HBM of the serving device, when the backend reports it."""
    import jax

    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        return stats.get("bytes_limit")
    return None


def device_hbm_in_use(device=None) -> Optional[int]:
    import jax

    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        return stats.get("bytes_in_use")
    return None


def host_memory_bytes() -> int:
    """Available HOST memory (bytes), 0 when unknowable.  The host-
    side twin of `device_hbm_bytes`: this ledger budgets the device;
    the kv tier (engine/kv_tier.py) budgets its spill file against
    what the host can give without swapping the serving process —
    same admission discipline, one level down."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


class InsufficientHBM(Exception):
    """No room for an admission.  `permanent` distinguishes "can
    NEVER fit" (bigger than the whole budget) from the transient
    no-evictable-victim case a waiting fault-in may retry."""

    permanent = False


@dataclass
class Residency:
    name: str
    bytes: int
    loaded_at: float
    last_used: float


class HBMManager:
    """Bin-packing accountant for model residency on one device/mesh.

    budget_bytes: capacity to pack into (defaults to `KFS_HBM_BUDGET`
    when set, else 90% of reported HBM, or a conservative 12 GiB if the
    backend doesn't report — v5e has 16 GiB).
    evict_cb: called with a model name when the manager decides to evict; the
    callback must actually free the model (engine.close() / offload()).
    victim_ok: optional admission-aware veto, consulted in LRU order
    while planning an eviction (called UNDER the ledger lock; the
    residency manager uses it to claim a victim atomically against a
    racing fault-in and to protect models with queued/in-flight work).
    A vetoed candidate is skipped — never evicted — and counted in
    `kfserving_tpu_hbm_eviction_skips_total`.
    victim_release: called for claimed-but-uncommitted victims when the
    admission plan fails after claiming them (undoes victim_ok's claim).
    """

    DEFAULT_BUDGET = 12 * 1024**3

    def __init__(self, budget_bytes: Optional[int] = None,
                 evict_cb: Optional[Callable[[str], None]] = None,
                 headroom: float = 0.10,
                 victim_ok: Optional[Callable[[str], bool]] = None):
        if budget_bytes is None:
            env = os.environ.get("KFS_HBM_BUDGET", "")
            if env:
                budget_bytes = int(float(env))
        if budget_bytes is None:
            total = device_hbm_bytes()
            budget_bytes = (int(total * (1 - headroom)) if total
                            else self.DEFAULT_BUDGET)
        self.budget_bytes = budget_bytes
        self.evict_cb = evict_cb
        self.victim_ok = victim_ok
        self.victim_release: Optional[Callable[[str], None]] = None
        self._resident: "OrderedDict[str, Residency]" = OrderedDict()
        self._lock = threading.Lock()
        # Lifetime eviction / admission-skip counts per model — the
        # ledger-side evidence the multimodel_density bench commits.
        self.evictions: Dict[str, int] = {}
        self.eviction_skips: Dict[str, int] = {}
        # Busy candidates already counted for a still-waiting
        # admission (admitted name -> candidates): a fault-in retries
        # admit every ~20 ms while its victims are busy, and the skip
        # metric counts each candidate once per admission episode,
        # not once per retry.
        self._skips_counted: Dict[str, set] = {}
        obs.hbm_budget_bytes().set(float(budget_bytes))

    @property
    def used_bytes(self) -> int:
        return sum(r.bytes for r in self._resident.values())

    @property
    def free_bytes(self) -> int:
        return self.budget_bytes - self.used_bytes

    def resident_models(self) -> List[str]:
        return list(self._resident.keys())

    def can_fit(self, nbytes: int) -> bool:
        return nbytes <= self.free_bytes

    def admit(self, name: str, nbytes: int, evict: bool = True) -> List[str]:
        """Account for a model of `nbytes` being loaded.

        Returns the list of models evicted to make room.  Raises
        InsufficientHBM if the model can never fit (bigger than budget) or
        eviction is disabled and there is no room.

        Three phases: RESERVE (plan victims and book `name`'s bytes
        under the lock — victims stay accounted), physical EVICTION
        (evict_cb outside the lock), COMMIT (victims leave the
        ledger).  Victims' bytes are not marked free until they are
        physically out of HBM: a concurrent admission on the other
        fault-in worker planning against freed-but-still-placed bytes
        would device_put straight into a transient overcommit/OOM.
        During the eviction window `used_bytes` therefore counts BOTH
        the victims and the incoming model — deliberately
        conservative.
        """
        victims: List[str] = []
        skipped: List[str] = []
        claimed: List[str] = []
        victim_entries: Dict[str, Residency] = {}
        try:
            with self._lock:
                if nbytes > self.budget_bytes:
                    err = InsufficientHBM(
                        f"model {name} needs {nbytes} bytes; budget is "
                        f"{self.budget_bytes}")
                    err.permanent = True
                    raise err
                # Plan admission against a scratch copy so a failed
                # admit leaves the books untouched (nothing is
                # physically evicted unless the plan fully reserves —
                # evict_cb never runs for a failed plan).  A reload of
                # `name` replaces its old entry rather than
                # double-counting it.
                plan = OrderedDict(
                    (k, v) for k, v in self._resident.items()
                    if k != name)
                while True:
                    plan_free = self.budget_bytes - sum(
                        r.bytes for r in plan.values())
                    if nbytes <= plan_free:
                        break
                    if not evict:
                        raise InsufficientHBM(
                            f"model {name} needs {nbytes} bytes; only "
                            f"{plan_free} free and eviction disabled")
                    # LRU order, admission-aware: victim_ok vetoes (and
                    # counts) candidates with queued/in-flight work; a
                    # passing candidate is CLAIMED under this lock, so
                    # a fault-in racing this eviction serializes on the
                    # ledger instead of serving a half-evicted model.
                    victim = None
                    for cand in plan:
                        if cand in skipped:
                            continue
                        if self.victim_ok is None or self.victim_ok(cand):
                            victim = cand
                            break
                        skipped.append(cand)
                    if victim is None:
                        raise InsufficientHBM(
                            f"model {name} needs {nbytes} bytes; no "
                            f"evictable victim ({len(skipped)} "
                            f"candidate(s) busy, nothing else to evict)")
                    plan.pop(victim)
                    victims.append(victim)
                    claimed.append(victim)
                # RESERVE: book the incoming bytes now; victims remain
                # in the ledger (claimed, so no other plan can take
                # them) until their physical offload lands below.
                now = time.time()
                self._resident.pop(name, None)
                self._resident[name] = Residency(name, nbytes, now, now)
                victim_entries = {v: self._resident[v] for v in victims}
                claimed = []  # reserved: this plan owns the victims now
        except BaseException:
            # Failed plan: undo victim_ok's claims so the candidates
            # rejoin the evictable set (books untouched by design).
            if self.victim_release is not None:
                for cand in claimed:
                    self.victim_release(cand)
            self._count_skips(name, skipped, done=False)
            raise
        self._count_skips(name, skipped, done=True)
        for victim in victims:
            logger.info("evicting model %s to fit %s", victim, name)
            if self.evict_cb:
                # Per-victim isolation: the plan is reserved, and the
                # callback's own cleanup demotes the record state —
                # one victim's failed physical offload must not strand
                # the REMAINING victims in their claimed ('evicting')
                # state with no offload ever coming, which would hang
                # every future fault-in of those models.
                try:
                    self.evict_cb(victim)
                except Exception:
                    logger.exception(
                        "evict callback failed for %s (entry released "
                        "anyway)", victim)
        if victims:
            # COMMIT: victims leave the ledger only now that they are
            # physically out of HBM.  Identity-checked pop: a victim
            # whose offload completed may have already been faulted
            # BACK in by a racing request (its record went host ->
            # faulting -> resident with a fresh ledger entry) — that
            # new residency must survive this commit.  Counter updates
            # stay under the lock (two fault-in workers race these
            # read-modify-writes).
            popped: List[str] = []
            with self._lock:
                for victim, entry in victim_entries.items():
                    if self._resident.get(victim) is entry:
                        self._resident.pop(victim)
                        popped.append(victim)
                    self.evictions[victim] = \
                        self.evictions.get(victim, 0) + 1
            for victim in victims:
                obs.hbm_evictions_total().labels(model=victim).inc()
            # Prune only victims that actually LEFT the ledger: one
            # re-admitted mid-eviction has a live entry (and a freshly
            # set gauge) this commit preserved.
            for victim in popped:
                obs.hbm_resident_bytes().prune(model=victim)
        obs.hbm_resident_bytes().labels(model=name).set(float(nbytes))
        return victims

    def _count_skips(self, name: str, skipped: List[str],
                     done: bool) -> None:
        """Count busy candidates an admission plan passed over — once
        per admission EPISODE, not per ~20 ms retry of a waiting
        fault-in.  `done` (plan committed) closes the episode."""
        with self._lock:
            counted = self._skips_counted.setdefault(name, set())
            fresh = [c for c in skipped if c not in counted]
            counted.update(fresh)
            if done:
                self._skips_counted.pop(name, None)
            for cand in fresh:
                self.eviction_skips[cand] = \
                    self.eviction_skips.get(cand, 0) + 1
        for cand in fresh:
            obs.hbm_eviction_skips_total().labels(
                model=cand, reason="busy").inc()

    def end_skip_episode(self, name: str) -> None:
        """Close a waiting admission's skip-dedup episode without a
        commit: the residency manager calls this when a fault-in
        exhausts its admit wait (or fails outright), so a LATER
        independent admission of the same model counts its busy
        victims afresh instead of being suppressed by the dead
        episode's memory."""
        with self._lock:
            self._skips_counted.pop(name, None)

    def touch(self, name: str) -> None:
        """Mark a model as recently used (moves it to MRU position)."""
        with self._lock:
            res = self._resident.get(name)
            if res is not None:
                res.last_used = time.time()
                self._resident.move_to_end(name)

    def release(self, name: str) -> None:
        with self._lock:
            self._resident.pop(name, None)
        # Prune, not zero: a released model must drop OUT of /metrics
        # (a forever-0 series per unloaded model would grow the scrape
        # unboundedly under multi-model churn).
        obs.hbm_resident_bytes().prune(model=name)

    def commit(self, staging: str, name: str,
               nbytes: Optional[int] = None) -> None:
        """Atomically replace ``name``'s entry with the ``staging`` entry.

        Used by zero-downtime reload: releasing old+staging and re-admitting
        would open a window where a concurrent admit claims the freed bytes
        and the re-admit fails after the new engine is already serving.
        Under the manager lock there is no such window.  ``nbytes``
        overrides the staged estimate with the measured size.
        """
        with self._lock:
            staged = self._resident.pop(staging, None)
            old = self._resident.pop(name, None)
            src = staged or old
            if src is None:
                return
            final = nbytes if nbytes is not None else src.bytes
            self._resident[name] = Residency(
                name, final, src.loaded_at, time.time())
        obs.hbm_resident_bytes().prune(model=staging)
        obs.hbm_resident_bytes().labels(model=name).set(float(final))

    def stats(self) -> Dict[str, float]:
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": self.used_bytes,
            "free_bytes": self.free_bytes,
            "resident_models": len(self._resident),
            "evictions_total": sum(self.evictions.values()),
            "eviction_skips_total": sum(self.eviction_skips.values()),
        }

    def debug(self) -> Dict[str, Any]:
        """The `/debug/cache` HBM snapshot: budget totals plus the
        per-model residency ledger in LRU order (index 0 = next
        eviction victim) — what the multi-model residency manager
        (ROADMAP item 4) will consume."""
        with self._lock:
            residents = [
                {"model": r.name, "bytes": r.bytes,
                 "loaded_at": round(r.loaded_at, 3),
                 "last_used": round(r.last_used, 3)}
                for r in self._resident.values()]
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": sum(r["bytes"] for r in residents),
            "resident": residents,
            "evictions": dict(self.evictions),
            "eviction_skips": dict(self.eviction_skips),
        }
