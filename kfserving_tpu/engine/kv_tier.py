"""Host-memory KV tier: capacity-evicted prefix blocks spill here and
fault back with one device_put-shaped insert on the next turn.

The paged pool's capacity evictions (generator.py `_alloc_block_locked`)
used to DROP the LRU cached prefix block — a returning multi-turn
conversation then pays a full re-prefill for context the device computed
seconds ago.  This tier keeps that state one level down: evicted blocks'
k/v land in a page-aligned host mmap keyed by the chain digest the
prefix index already computes, and the admission plan probes
device index → host tier → re-prefill.  A warm host fault is one mmap
read + one jitted pool insert (milliseconds) versus a multi-second
re-prefill of a long history.

Robustness contract (the point of this module, per ISSUE 16):

- **Transactional spill**: the in-memory index entry publishes only
  AFTER the slot's full payload is written — a half-spilled chain can
  never be read; a failed spill leaves the tier exactly as it was and
  the eviction degrades to the drop-on-evict baseline.
- **Transactional fault-back**: `begin_fault`/`end_fault` bracket a
  read; a failed fault-back drops the (now-suspect) entry so the
  replanned admission misses the tier and falls through to a normal
  re-prefill.
- **Bounded LRU ledger with admission-aware eviction**: the tier holds
  at most `capacity_blocks` entries; admission of a new spill evicts
  the LRU entry but never one mid-fault-in (the `engine/hbm.py`
  victim_ok discipline, host-side), and the whole file is clamped
  against the host's available memory (`hbm.host_memory_bytes`).
- **Single-flight fault-in**: `begin_fault` refcounts in-flight chains;
  concurrent returning turns coalesce on the same physical read
  (counted as outcome=coalesced).
- **Observable**: occupancy/spill/fault registry families, a `debug()`
  block federated under `/debug/cache`, and a flight-recorder pin when
  fault-backs storm (`KFS_KV_TIER_STORM_*` — a storm means the device
  pool is churning conversations through the tier faster than they
  finish, the thrash evidence an operator needs pinned).

Storage follows PR 7's param-cache mmap discipline: page-aligned slot
stride, one preallocated file, read-only consumers never see torn
writes (publication is the in-memory index, which dies with the
process — the file carries no cross-restart authority).

Threading: `put()` runs on the engine's fetch executor, `read()` on the
enqueue executor, `contains`/`begin_fault` on the scheduler loop — all
state is guarded by one lock, and every payload copy in or out of the
mmap happens under it (slots are small: one block's k/v).  Nothing here
ever runs ON the scheduler loop thread except dict probes.
"""

import logging
import mmap
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, Optional

from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger(__name__)

# Page alignment for slot strides (PR 7's param_cache discipline): the
# kernel faults whole pages, so a slot straddling page boundaries costs
# an extra fault per read for no layout benefit.
_ALIGN = 4096

# Never let the spill file claim more than this fraction of the host's
# available memory — the tier is a cache under the serving process, not
# a tenant that evicts it.
_HOST_MEM_FRACTION = 0.5


def _env_int(name: str, default: int) -> int:
    try:
        return int(float(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


class HostKVTier:
    """Bounded host-memory ledger of spilled KV blocks, chain-keyed.

    `block_bytes` is the exact payload size of one block's k/v across
    all layers; `capacity_blocks` bounds the ledger (clamped against
    available host memory).  The tier never touches device state — the
    engine owns gather/insert dispatches; this class owns bytes,
    the LRU index, and the telemetry.
    """

    def __init__(self, *, block_bytes: int, capacity_blocks: int,
                 directory: Optional[str] = None,
                 model: str = "decoder"):
        if block_bytes <= 0:
            raise ValueError("block_bytes must be positive")
        if capacity_blocks <= 0:
            raise ValueError("capacity_blocks must be positive")
        self.model = model
        self.block_bytes = int(block_bytes)
        self.slot_bytes = (
            (self.block_bytes + _ALIGN - 1) // _ALIGN * _ALIGN)
        # hbm.py ledger interplay: the device ledger budgets HBM, this
        # one budgets host RAM — clamp the file against what the host
        # can actually give without swapping the serving process out.
        from kfserving_tpu.engine.hbm import host_memory_bytes

        avail = host_memory_bytes()
        capacity_blocks = int(capacity_blocks)
        if avail > 0:
            max_blocks = int(avail * _HOST_MEM_FRACTION
                             // self.slot_bytes)
            if 0 < max_blocks < capacity_blocks:
                logger.warning(
                    "kv tier capacity clamped %d -> %d blocks "
                    "(host memory available: %.1f GiB)",
                    capacity_blocks, max_blocks, avail / 1024**3)
                capacity_blocks = max_blocks
        self.capacity_blocks = max(1, capacity_blocks)

        self._owns_dir = directory is None
        directory = directory or tempfile.mkdtemp(
            prefix=f"kfs-kvtier-{model}-")
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "kv_tier.bin")
        size = self.capacity_blocks * self.slot_bytes
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)  # sparse until slots are written
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)

        self._lock = threading.Lock()
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._free: deque = deque(range(self.capacity_blocks))
        # chain -> in-flight fault-back refcount: eviction never
        # victimizes these (admission-aware), and a second concurrent
        # fault on the same chain is counted as coalesced.
        self._inflight: Dict[bytes, int] = {}
        self._closed = False

        # -- counters (ints under the lock; registry twins emitted at
        # the event site) ----------------------------------------------
        self.spills = 0
        self.spill_failures = 0
        self.spill_duplicates = 0
        self.faults = 0            # physically read-back blocks
        self.coalesced = 0         # riders on an in-flight fault
        self.fault_failures = 0
        self.evictions = 0         # LRU capacity evictions
        self.eviction_skips = 0    # vetoed: victim mid-fault-in
        self.dropped = 0           # entries dropped after a failed
        #                            fault-back (presumed unusable)
        self._fault_ms: deque = deque(maxlen=512)

        # -- fault-back storm detection (flight-recorder pin) ----------
        self.storm_window_s = float(os.environ.get(
            "KFS_KV_TIER_STORM_WINDOW_S", "10"))
        self.storm_threshold = _env_int(
            "KFS_KV_TIER_STORM_THRESHOLD", 32)
        self._fault_times: deque = deque(maxlen=1024)
        self._storm_pinned_at = 0.0
        self._flight_recorder = None

    # -- wiring ------------------------------------------------------------
    def attach_flight_recorder(self, recorder) -> None:
        """Point storm pins at a server's flight recorder (app.py
        attaches its monitoring recorder at start)."""
        self._flight_recorder = recorder

    # -- probes (scheduler-loop safe: dict lookups only) -------------------
    def contains(self, chain: bytes) -> bool:
        with self._lock:
            return chain in self._index

    def begin_fault(self, chain: bytes) -> bool:
        """Mark `chain` in-flight for fault-back (single-flight
        bracket).  Returns False when the tier no longer holds it —
        the caller falls through to re-prefill.  While in-flight the
        entry cannot be evicted by a concurrent spill admission."""
        with self._lock:
            if chain not in self._index:
                return False
            self._inflight[chain] = self._inflight.get(chain, 0) + 1
            return True

    def note_coalesced(self, blocks: int = 1) -> None:
        with self._lock:
            self.coalesced += blocks
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="coalesced").inc(blocks)

    def end_fault(self, chain: bytes) -> None:
        with self._lock:
            n = self._inflight.get(chain, 0) - 1
            if n <= 0:
                self._inflight.pop(chain, None)
            else:
                self._inflight[chain] = n

    # -- spill (fetch-executor thread) -------------------------------------
    def put(self, chain: bytes, payload: bytes) -> bool:
        """Admit one block's payload.  Transactional: the index entry
        publishes only after the slot holds the complete payload, so a
        failure at any point leaves the tier without the chain (the
        eviction that produced it degrades to a plain drop).  Returns
        False on failure; never raises."""
        try:
            if len(payload) != self.block_bytes:
                raise ValueError(
                    f"payload {len(payload)}B != block {self.block_bytes}B")
            with self._lock:
                if self._closed:
                    return False
                if chain in self._index:
                    # Already safe (a fault-back re-registered the
                    # chain on device and it was re-evicted before
                    # this late spill resolved).
                    self.spill_duplicates += 1
                    obs.generator_kv_tier_spills_total().labels(
                        model=self.model, outcome="duplicate").inc()
                    return True
                slot = self._reserve_slot_locked()
                if slot is None:
                    raise RuntimeError(
                        "kv tier full: every entry is mid-fault-in")
                off = slot * self.slot_bytes
                self._mm[off:off + self.block_bytes] = payload
                # Publication point: a reader can only find the chain
                # AFTER the full payload landed.
                self._index[chain] = slot
                self._index.move_to_end(chain)
                self.spills += 1
            obs.generator_kv_tier_spills_total().labels(
                model=self.model, outcome="spilled").inc()
            self._publish_occupancy()
            return True
        except Exception:
            logger.exception("kv tier spill failed (%s)", self.model)
            with self._lock:
                self.spill_failures += 1
            obs.generator_kv_tier_spills_total().labels(
                model=self.model, outcome="failed").inc()
            return False

    def note_spill_failure(self, blocks: int = 1) -> None:
        """Spills aborted before ever reaching put() — e.g. the
        `engine.kv_spill` chaos site firing on the gather fetch.  The
        evictions degrade to plain drops; this keeps the tier's
        attempt accounting honest about it."""
        with self._lock:
            self.spill_failures += blocks
        obs.generator_kv_tier_spills_total().labels(
            model=self.model, outcome="failed").inc(blocks)

    def _reserve_slot_locked(self) -> Optional[int]:
        if self._free:
            return self._free.popleft()
        # LRU eviction, admission-aware: never victimize an entry a
        # fault-back is reading right now (hbm.py's victim_ok veto,
        # host-side) — skip it and take the next-oldest.
        for chain in self._index:
            if chain in self._inflight:
                self.eviction_skips += 1
                obs.generator_kv_tier_evictions_total().labels(
                    model=self.model, reason="skipped_inflight").inc()
                continue
            slot = self._index.pop(chain)
            self.evictions += 1
            obs.generator_kv_tier_evictions_total().labels(
                model=self.model, reason="capacity").inc()
            return slot
        return None

    # -- fault-back (enqueue-executor thread) ------------------------------
    def read(self, chain: bytes) -> bytes:
        """One block's payload (a bytes copy — the mmap slot can be
        recycled by a concurrent spill the moment the lock drops).
        Raises KeyError when the chain is gone (evicted between the
        plan's probe and this read) — the caller's fault-back fails
        transactionally and the turn re-prefills."""
        with self._lock:
            slot = self._index.get(chain)
            if slot is None:
                raise KeyError(chain.hex())
            off = slot * self.slot_bytes
            payload = bytes(self._mm[off:off + self.block_bytes])
            self._index.move_to_end(chain)
        return payload

    def note_faultback(self, blocks: int, elapsed_ms: float) -> None:
        """Account one successful fault-back batch: `blocks` physical
        reads landed on device in `elapsed_ms`."""
        with self._lock:
            self.faults += blocks
            self._fault_ms.append(elapsed_ms)
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="faulted").inc(blocks)
        obs.generator_kv_tier_faultback_ms().labels(
            model=self.model).observe(elapsed_ms)
        self._note_storm(blocks)

    def note_fault_failure(self, blocks: int = 1) -> None:
        with self._lock:
            self.fault_failures += blocks
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="failed").inc(blocks)

    def drop(self, chain: bytes) -> None:
        """Remove an entry (failed fault-back: the payload is suspect
        — the replanned turn must MISS the tier and re-prefill)."""
        with self._lock:
            slot = self._index.pop(chain, None)
            if slot is None:
                return
            self._free.append(slot)
            self.dropped += 1
        obs.generator_kv_tier_evictions_total().labels(
            model=self.model, reason="faultback_failed").inc()
        self._publish_occupancy()

    # -- storm pin ---------------------------------------------------------
    def _note_storm(self, blocks: int) -> None:
        now = time.monotonic()
        for _ in range(blocks):
            self._fault_times.append(now)
        recent = sum(1 for t in self._fault_times
                     if now - t <= self.storm_window_s)
        if recent <= self.storm_threshold:
            return
        recorder = self._flight_recorder
        # One pin per storm window, not one per fault in it.
        if recorder is None or \
                now - self._storm_pinned_at < self.storm_window_s:
            return
        self._storm_pinned_at = now
        recorder.record({
            "kind": "kv_tier_faultback_storm",
            "model": self.model,
            "faults_in_window": recent,
            "window_s": self.storm_window_s,
            "host_tier": self.debug(),
        }, pin="kv_faultback_storm")
        logger.warning(
            "kv tier fault-back storm: %d blocks in %.0fs (device "
            "pool churns conversations through the host tier — "
            "flight-recorder entry pinned)",
            recent, self.storm_window_s)

    # -- introspection -----------------------------------------------------
    def _publish_occupancy(self) -> None:
        with self._lock:
            used = len(self._index)
        obs.generator_kv_tier_blocks().labels(
            model=self.model).set(float(used))
        obs.generator_kv_tier_occupancy_ratio().labels(
            model=self.model).set(
                min(1.0, used / max(1, self.capacity_blocks)))

    def debug(self) -> Dict[str, Any]:
        """The `host_tier` block of `/debug/cache`, federated by the
        router under the `replica` label."""
        with self._lock:
            samples = sorted(self._fault_ms)

            def pct(q: float) -> float:
                if not samples:
                    return 0.0
                return round(samples[min(len(samples) - 1,
                                         int(len(samples) * q))], 3)

            return {
                "capacity_blocks": self.capacity_blocks,
                "used_blocks": len(self._index),
                "block_bytes": self.block_bytes,
                "slot_bytes": self.slot_bytes,
                "file_bytes": self.capacity_blocks * self.slot_bytes,
                "inflight_faults": len(self._inflight),
                "spills": self.spills,
                "spill_failures": self.spill_failures,
                "spill_duplicates": self.spill_duplicates,
                "faulted_blocks": self.faults,
                "coalesced_blocks": self.coalesced,
                "fault_failures": self.fault_failures,
                "evictions": self.evictions,
                "eviction_skips": self.eviction_skips,
                "dropped": self.dropped,
                "faultback_ms": {"p50": pct(0.50), "p99": pct(0.99)},
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._index.clear()
            self._inflight.clear()
            try:
                self._mm.close()
            except Exception:
                pass
        try:
            os.unlink(self.path)
            if self._owns_dir:
                os.rmdir(os.path.dirname(self.path))
        except OSError:
            pass
