"""Host-memory KV tier: capacity-evicted prefix blocks spill here and
fault back with one device_put-shaped insert on the next turn.

The paged pool's capacity evictions (generator.py `_alloc_block_locked`)
used to DROP the LRU cached prefix block — a returning multi-turn
conversation then pays a full re-prefill for context the device computed
seconds ago.  This tier keeps that state one level down: evicted blocks'
k/v land in a page-aligned host mmap keyed by the chain digest the
prefix index already computes, and the admission plan probes
device index → host tier → re-prefill.  A warm host fault is one mmap
read + one jitted pool insert (milliseconds) versus a multi-second
re-prefill of a long history.

Robustness contract (the point of this module, per ISSUEs 16 and 19):

- **Transactional spill**: the in-memory index entry publishes only
  AFTER the slot's full payload is written — a half-spilled chain can
  never be read; a failed spill leaves the tier exactly as it was and
  the eviction degrades to the drop-on-evict baseline.
- **Transactional fault-back**: `begin_fault`/`end_fault` bracket a
  read; a failed fault-back drops the (now-suspect) entry so the
  replanned admission misses the tier and falls through to a normal
  re-prefill.
- **Bounded LRU ledger with admission-aware eviction**: the tier holds
  at most `capacity_blocks` entries; admission of a new spill evicts
  the LRU entry but never one mid-fault-in (the `engine/hbm.py`
  victim_ok discipline, host-side), and the whole file is clamped
  against the host's available memory (`hbm.host_memory_bytes`).
- **Single-flight fault-in**: `begin_fault` refcounts in-flight chains;
  concurrent returning turns coalesce on the same physical read
  (counted as outcome=coalesced).
- **Durable handoff (ISSUE 19)**: under a persistent directory
  (`KFS_KV_TIER_DIR` / an explicit `directory=`), each process writes
  its payload file plus a versioned, crash-safe JSONL *manifest*
  (`kv_tier-<model>-<nonce>.manifest`) and holds an exclusive
  `flock` on it for its lifetime.  The flock IS the liveness
  authority: it releases on ANY process death, including SIGKILL.  A
  successor process (armed standby, promoted crash-failover survivor,
  or plain restart) adopts every unlocked generation it finds — every
  entry is digest-verified against the manifest record before
  admission, torn/truncated/corrupt/version-skewed entries drop
  individually (never served, never crash the boot), and the drained
  generation's files self-delete.  Ephemeral tiers (no directory
  given) keep the pre-ISSUE-19 behavior: a private tempdir, no
  manifest, nothing survives the process.
- **Observable**: occupancy/spill/fault registry families plus the
  `kv_handoff_reattached_blocks_total` adoption outcomes, a `debug()`
  block federated under `/debug/cache`, and a flight-recorder pin when
  fault-backs storm (`KFS_KV_TIER_STORM_*`).

Storage follows PR 7's param-cache mmap discipline: page-aligned slot
stride, one preallocated file, read-only consumers never see torn
writes (publication is the in-memory index; in persistent mode the
manifest record lands BEFORE the index publishes, so the on-disk view
never claims a chain whose payload isn't fully written — a crash
between payload write and manifest append leaves an unreferenced slot,
and a crash mid-append leaves a torn JSON line the replay skips).

Path containment (ISSUE 19 satellite): the configured directory is
resolved once; every file this module creates, reads, or deletes is
containment-checked against that resolved root — a symlink smuggled
into the tier dir cannot steer a delete outside it, and a
non-directory target fails construction with a clear error instead of
a traceback from mmap.

Threading: `put()` runs on the engine's fetch executor, `read()` on the
enqueue executor, `contains`/`begin_fault` on the scheduler loop — all
state is guarded by one lock, and every payload copy in or out of the
mmap happens under it (slots are small: one block's k/v).  Nothing here
ever runs ON the scheduler loop thread except dict probes.
"""

import fcntl
import hashlib
import json
import logging
import mmap
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from kfserving_tpu.observability import metrics as obs

logger = logging.getLogger(__name__)

# Page alignment for slot strides (PR 7's param_cache discipline): the
# kernel faults whole pages, so a slot straddling page boundaries costs
# an extra fault per read for no layout benefit.
_ALIGN = 4096

# Never let the spill file claim more than this fraction of the host's
# available memory — the tier is a cache under the serving process, not
# a tenant that evicts it.
_HOST_MEM_FRACTION = 0.5

# Manifest record schema version.  Replay skips records whose `v`
# differs (counted as version_skew) — a rolling upgrade where old and
# new replicas share one tier dir drops only the unreadable entries.
_MANIFEST_V = 1

# Payload digests are 16-byte blake2b — same construction as the
# prefix-index chain digests, so verification cost stays proportional
# to one block's bytes.
_DIGEST_SIZE = 16

_ADOPT_OUTCOMES = ("adopted", "duplicate", "corrupt", "truncated",
                   "torn", "version_skew", "dropped_capacity",
                   "failed")


def _env_int(name: str, default: int) -> int:
    try:
        return int(float(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


def payload_digest(payload: bytes) -> str:
    """Hex digest a block payload is verified against: on manifest
    replay, on peer-transfer receipt (`/kv/chains/<chain>`), and in
    the response header the peer endpoint serves."""
    return hashlib.blake2b(payload, digest_size=_DIGEST_SIZE).hexdigest()


class HostKVTier:
    """Bounded host-memory ledger of spilled KV blocks, chain-keyed.

    `block_bytes` is the exact payload size of one block's k/v across
    all layers; `capacity_blocks` bounds the ledger (clamped against
    available host memory).  The tier never touches device state — the
    engine owns gather/insert dispatches; this class owns bytes,
    the LRU index, the durable manifest, and the telemetry.
    """

    def __init__(self, *, block_bytes: int, capacity_blocks: int,
                 directory: Optional[str] = None,
                 model: str = "decoder"):
        if block_bytes <= 0:
            raise ValueError("block_bytes must be positive")
        if capacity_blocks <= 0:
            raise ValueError("capacity_blocks must be positive")
        self.model = model
        self.block_bytes = int(block_bytes)
        self.slot_bytes = (
            (self.block_bytes + _ALIGN - 1) // _ALIGN * _ALIGN)
        # hbm.py ledger interplay: the device ledger budgets HBM, this
        # one budgets host RAM — clamp the file against what the host
        # can actually give without swapping the serving process out.
        from kfserving_tpu.engine.hbm import host_memory_bytes

        avail = host_memory_bytes()
        capacity_blocks = int(capacity_blocks)
        if avail > 0:
            max_blocks = int(avail * _HOST_MEM_FRACTION
                             // self.slot_bytes)
            if 0 < max_blocks < capacity_blocks:
                logger.warning(
                    "kv tier capacity clamped %d -> %d blocks "
                    "(host memory available: %.1f GiB)",
                    capacity_blocks, max_blocks, avail / 1024**3)
                capacity_blocks = max_blocks
        self.capacity_blocks = max(1, capacity_blocks)

        # A caller-provided directory means the tier is PERSISTENT:
        # its files outlive this process for a successor to adopt.  No
        # directory means the pre-ISSUE-19 ephemeral tempdir.
        self._owns_dir = directory is None
        self.persistent = directory is not None
        if directory is not None:
            directory = os.path.realpath(directory)
            if os.path.exists(directory) and \
                    not os.path.isdir(directory):
                raise ValueError(
                    f"KV tier dir {directory!r} exists and is not a "
                    "directory — point KFS_KV_TIER_DIR (or the "
                    "model's host_tier_dir) at a directory path")
        else:
            directory = os.path.realpath(tempfile.mkdtemp(
                prefix=f"kfs-kvtier-{model}-"))
        os.makedirs(directory, exist_ok=True)
        self.directory = directory

        if self.persistent:
            # Per-process generation naming: pid + random nonce, so
            # two replicas sharing the dir never collide and a
            # successor can tell its own files from a predecessor's.
            nonce = f"{os.getpid():x}-{os.urandom(4).hex()}"
            base = f"kv_tier-{model}-{nonce}"
        else:
            base = "kv_tier"
        self.path = os.path.join(directory, base + ".bin")
        size = self.capacity_blocks * self.slot_bytes
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)  # sparse until slots are written
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)

        self._lock = threading.Lock()
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._free: deque = deque(range(self.capacity_blocks))
        # chain -> in-flight fault-back refcount: eviction never
        # victimizes these (admission-aware), and a second concurrent
        # fault on the same chain is counted as coalesced.
        self._inflight: Dict[bytes, int] = {}
        self._closed = False

        # -- durable manifest (persistent mode only) -------------------
        self._manifest_path = os.path.join(
            directory, base + ".manifest")
        self._mfd: Optional[int] = None
        self._digests: Dict[bytes, str] = {}
        self._manifest_records = 0
        self.manifest_failures = 0
        # Compaction bound: the manifest is append-only, so a
        # long-lived churny tier would grow it without this.
        self._manifest_max_records = max(
            1024, 8 * self.capacity_blocks)
        if self.persistent:
            self._mfd = os.open(
                self._manifest_path,
                os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o600)
            # The flock IS the liveness authority for adoption: held
            # for this process's lifetime, auto-released on any death
            # (SIGKILL included) — a successor that can take it knows
            # the generation is orphaned.
            fcntl.flock(self._mfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            header = {
                "kind": "kfs-kv-tier", "v": _MANIFEST_V,
                "model": self.model,
                "block_bytes": self.block_bytes,
                "slot_bytes": self.slot_bytes,
                "capacity_blocks": self.capacity_blocks,
            }
            os.write(self._mfd,
                     (json.dumps(header) + "\n").encode("utf-8"))
            self._manifest_records = 1

        # -- counters (ints under the lock; registry twins emitted at
        # the event site) ----------------------------------------------
        self.spills = 0
        self.spill_failures = 0
        self.spill_duplicates = 0
        self.faults = 0            # physically read-back blocks
        self.coalesced = 0         # riders on an in-flight fault
        self.fault_failures = 0
        self.evictions = 0         # LRU capacity evictions
        self.eviction_skips = 0    # vetoed: victim mid-fault-in
        self.dropped = 0           # entries dropped after a failed
        #                            fault-back (presumed unusable)
        self._fault_ms: deque = deque(maxlen=512)

        # Lifetime adoption tallies (per-outcome block counts plus
        # generation-level bookkeeping), surfaced in debug().
        self.handoff: Dict[str, int] = {
            k: 0 for k in _ADOPT_OUTCOMES}
        self.handoff["generations_adopted"] = 0
        self.handoff["generations_live"] = 0
        self.handoff["generations_rejected"] = 0

        # -- fault-back storm detection (flight-recorder pin) ----------
        self.storm_window_s = float(os.environ.get(
            "KFS_KV_TIER_STORM_WINDOW_S", "10"))
        self.storm_threshold = _env_int(
            "KFS_KV_TIER_STORM_THRESHOLD", 32)
        self._fault_times: deque = deque(maxlen=1024)
        self._storm_pinned_at = 0.0
        self._flight_recorder = None

        if self.persistent:
            # Boot-time adoption: drain every orphaned predecessor
            # generation in the shared dir (exclusive-swap successors
            # and plain restarts get their warm chains here; warm
            # swaps and crash promotions re-scan via reattach()).
            self._adopt_generations()

    # -- wiring ------------------------------------------------------------
    def attach_flight_recorder(self, recorder) -> None:
        """Point storm pins at a server's flight recorder (app.py
        attaches its monitoring recorder at start)."""
        self._flight_recorder = recorder

    # -- probes (scheduler-loop safe: dict lookups only) -------------------
    def contains(self, chain: bytes) -> bool:
        with self._lock:
            return chain in self._index

    def chains(self) -> List[str]:
        """Hex chain digests currently resident (MRU last) — the
        peer-transfer index `GET /kv/chains` serves."""
        with self._lock:
            return [c.hex() for c in self._index]

    def begin_fault(self, chain: bytes) -> bool:
        """Mark `chain` in-flight for fault-back (single-flight
        bracket).  Returns False when the tier no longer holds it —
        the caller falls through to re-prefill.  While in-flight the
        entry cannot be evicted by a concurrent spill admission."""
        with self._lock:
            if chain not in self._index:
                return False
            self._inflight[chain] = self._inflight.get(chain, 0) + 1
            return True

    def note_coalesced(self, blocks: int = 1) -> None:
        with self._lock:
            self.coalesced += blocks
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="coalesced").inc(blocks)

    def end_fault(self, chain: bytes) -> None:
        with self._lock:
            n = self._inflight.get(chain, 0) - 1
            if n <= 0:
                self._inflight.pop(chain, None)
            else:
                self._inflight[chain] = n

    # -- path containment (ISSUE 19 satellite) -----------------------------
    def _contained(self, path: str) -> bool:
        """True when `path` resolves inside the tier directory — the
        gate every unlink/rename candidate passes before the
        filesystem call (a symlink planted in a shared tier dir must
        not steer a delete outside it)."""
        try:
            rp = os.path.realpath(path)
            return os.path.commonpath(
                [rp, self.directory]) == self.directory
        except (OSError, ValueError):
            return False

    # -- durable manifest --------------------------------------------------
    def _manifest_append_locked(self, record: Dict[str, Any]) -> None:
        """Append one record (caller holds the lock).  A failed append
        is non-fatal — the in-memory tier keeps serving; the entry
        just won't survive a handoff (counted)."""
        if self._mfd is None:
            return
        try:
            os.write(self._mfd,
                     (json.dumps(record) + "\n").encode("utf-8"))
            self._manifest_records += 1
            if self._manifest_records > self._manifest_max_records:
                self._compact_manifest_locked()
        except OSError:
            self.manifest_failures += 1

    def _compact_manifest_locked(self) -> None:
        """Rewrite the manifest as header + one put per live entry.
        The tmp file is flocked BEFORE the rename so there is no
        instant where the published manifest is unlocked (a scanning
        successor would otherwise adopt a live generation)."""
        tmp = self._manifest_path + ".tmp"
        if not (self._contained(tmp)
                and self._contained(self._manifest_path)):
            self.manifest_failures += 1
            return
        header = {
            "kind": "kfs-kv-tier", "v": _MANIFEST_V,
            "model": self.model,
            "block_bytes": self.block_bytes,
            "slot_bytes": self.slot_bytes,
            "capacity_blocks": self.capacity_blocks,
        }
        lines = [json.dumps(header)]
        for chain, slot in self._index.items():
            digest = self._digests.get(chain)
            if digest is None:
                continue
            lines.append(json.dumps({
                "op": "put", "v": _MANIFEST_V, "chain": chain.hex(),
                "slot": slot, "digest": digest}))
        fd = os.open(tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC
                     | os.O_APPEND, 0o600)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            os.write(fd, ("\n".join(lines) + "\n").encode("utf-8"))
            os.replace(tmp, self._manifest_path)
        except OSError:
            self.manifest_failures += 1
            os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        old = self._mfd
        self._mfd = fd
        self._manifest_records = len(lines)
        if old is not None:
            try:
                os.close(old)
            except OSError:
                pass

    # -- spill (fetch-executor thread) -------------------------------------
    def put(self, chain: bytes, payload: bytes) -> bool:
        """Admit one block's payload.  Transactional: the index entry
        publishes only after the slot holds the complete payload (and,
        in persistent mode, after the manifest records it), so a
        failure at any point leaves the tier without the chain (the
        eviction that produced it degrades to a plain drop).  Returns
        False on failure; never raises."""
        try:
            if len(payload) != self.block_bytes:
                raise ValueError(
                    f"payload {len(payload)}B != block {self.block_bytes}B")
            with self._lock:
                if self._closed:
                    return False
                if chain in self._index:
                    # Already safe (a fault-back re-registered the
                    # chain on device and it was re-evicted before
                    # this late spill resolved).
                    self.spill_duplicates += 1
                    obs.generator_kv_tier_spills_total().labels(
                        model=self.model, outcome="duplicate").inc()
                    return True
                slot = self._reserve_slot_locked()
                if slot is None:
                    raise RuntimeError(
                        "kv tier full: every entry is mid-fault-in")
                off = slot * self.slot_bytes
                self._mm[off:off + self.block_bytes] = payload
                if self.persistent:
                    digest = payload_digest(payload)
                    self._digests[chain] = digest
                    # Record BEFORE publication: the on-disk view
                    # never claims a chain whose payload isn't fully
                    # written (replay digest-verifies regardless).
                    self._manifest_append_locked({
                        "op": "put", "v": _MANIFEST_V,
                        "chain": chain.hex(), "slot": slot,
                        "digest": digest})
                # Publication point: a reader can only find the chain
                # AFTER the full payload landed.
                self._index[chain] = slot
                self._index.move_to_end(chain)
                self.spills += 1
            obs.generator_kv_tier_spills_total().labels(
                model=self.model, outcome="spilled").inc()
            self._publish_occupancy()
            return True
        except Exception:
            logger.exception("kv tier spill failed (%s)", self.model)
            with self._lock:
                self.spill_failures += 1
            obs.generator_kv_tier_spills_total().labels(
                model=self.model, outcome="failed").inc()
            return False

    def note_spill_failure(self, blocks: int = 1) -> None:
        """Spills aborted before ever reaching put() — e.g. the
        `engine.kv_spill` chaos site firing on the gather fetch.  The
        evictions degrade to plain drops; this keeps the tier's
        attempt accounting honest about it."""
        with self._lock:
            self.spill_failures += blocks
        obs.generator_kv_tier_spills_total().labels(
            model=self.model, outcome="failed").inc(blocks)

    def _reserve_slot_locked(self) -> Optional[int]:
        if self._free:
            return self._free.popleft()
        # LRU eviction, admission-aware: never victimize an entry a
        # fault-back is reading right now (hbm.py's victim_ok veto,
        # host-side) — skip it and take the next-oldest.
        for chain in self._index:
            if chain in self._inflight:
                self.eviction_skips += 1
                obs.generator_kv_tier_evictions_total().labels(
                    model=self.model, reason="skipped_inflight").inc()
                continue
            slot = self._index.pop(chain)
            self._digests.pop(chain, None)
            # No drop record: the put that triggered this eviction
            # writes a put record for the SAME slot, and replay is
            # last-writer-wins per slot — the evicted chain is
            # superseded on disk the moment the admission lands.  A
            # crash in between leaves a record whose payload digest
            # no longer matches; replay drops it as corrupt.
            self.evictions += 1
            obs.generator_kv_tier_evictions_total().labels(
                model=self.model, reason="capacity").inc()
            return slot
        return None

    # -- fault-back (enqueue-executor thread) ------------------------------
    def read(self, chain: bytes) -> bytes:
        """One block's payload (a bytes copy — the mmap slot can be
        recycled by a concurrent spill the moment the lock drops).
        Raises KeyError when the chain is gone (evicted between the
        plan's probe and this read) — the caller's fault-back fails
        transactionally and the turn re-prefills."""
        with self._lock:
            slot = self._index.get(chain)
            if slot is None:
                raise KeyError(chain.hex())
            off = slot * self.slot_bytes
            payload = bytes(self._mm[off:off + self.block_bytes])
            self._index.move_to_end(chain)
        return payload

    def note_faultback(self, blocks: int, elapsed_ms: float) -> None:
        """Account one successful fault-back batch: `blocks` physical
        reads landed on device in `elapsed_ms`."""
        with self._lock:
            self.faults += blocks
            self._fault_ms.append(elapsed_ms)
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="faulted").inc(blocks)
        obs.generator_kv_tier_faultback_ms().labels(
            model=self.model).observe(elapsed_ms)
        self._note_storm(blocks)

    def note_fault_failure(self, blocks: int = 1) -> None:
        with self._lock:
            self.fault_failures += blocks
        obs.generator_kv_tier_faultbacks_total().labels(
            model=self.model, outcome="failed").inc(blocks)

    def drop(self, chain: bytes) -> None:
        """Remove an entry (failed fault-back: the payload is suspect
        — the replanned turn must MISS the tier and re-prefill)."""
        with self._lock:
            slot = self._index.pop(chain, None)
            if slot is None:
                return
            self._free.append(slot)
            self._digests.pop(chain, None)
            if self.persistent:
                self._manifest_append_locked({
                    "op": "drop", "v": _MANIFEST_V,
                    "chain": chain.hex()})
            self.dropped += 1
        obs.generator_kv_tier_evictions_total().labels(
            model=self.model, reason="faultback_failed").inc()
        self._publish_occupancy()

    # -- durable handoff: adopting predecessor generations -----------------
    def reattach(self) -> Dict[str, int]:
        """Re-scan the tier dir and adopt any orphaned predecessor
        generation (POST /kv/reattach; the orchestrator calls it on
        the successor after a warm swap or crash promotion).  Returns
        this invocation's per-outcome block tallies.  No-op for
        ephemeral tiers."""
        if not self.persistent:
            return {}
        return self._adopt_generations()

    def _adopt_generations(self) -> Dict[str, int]:
        out: Dict[str, int] = {k: 0 for k in _ADOPT_OUTCOMES}
        out["generations_adopted"] = 0
        out["generations_live"] = 0
        out["generations_rejected"] = 0
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        own = os.path.realpath(self._manifest_path)
        for name in names:
            if not (name.startswith("kv_tier-")
                    and name.endswith(".manifest")):
                continue
            mpath = os.path.join(self.directory, name)
            if os.path.realpath(mpath) == own:
                continue
            if not self._contained(mpath):
                out["generations_rejected"] += 1
                continue
            self._adopt_one(mpath, out)
        for outcome in _ADOPT_OUTCOMES:
            if out[outcome]:
                obs.kv_handoff_reattached_blocks_total().labels(
                    model=self.model, outcome=outcome).inc(
                        out[outcome])
        with self._lock:
            for k, v in out.items():
                self.handoff[k] = self.handoff.get(k, 0) + v
        if out["adopted"] or out["generations_rejected"] or any(
                out[k] for k in ("corrupt", "truncated", "torn",
                                 "version_skew")):
            logger.info(
                "kv tier handoff (%s): adopted=%d duplicate=%d "
                "corrupt=%d truncated=%d torn=%d version_skew=%d "
                "dropped_capacity=%d generations=%d/%d live=%d",
                self.model, out["adopted"], out["duplicate"],
                out["corrupt"], out["truncated"], out["torn"],
                out["version_skew"], out["dropped_capacity"],
                out["generations_adopted"],
                out["generations_adopted"]
                + out["generations_rejected"],
                out["generations_live"])
        recorder = self._flight_recorder
        if recorder is not None and (
                out["adopted"] or out["generations_rejected"]):
            try:
                recorder.record({
                    "kind": "kv_handoff_reattach",
                    "model": self.model, **out,
                }, pin="kv_handoff_reattach")
            except Exception:
                pass
        return out

    def _adopt_one(self, mpath: str, out: Dict[str, int]) -> None:
        """Adopt (or discard) one foreign generation.  The flock probe
        decides everything: held → the owner is alive, skip entirely;
        acquired → the generation is orphaned, drain it and delete its
        files.  Every admitted payload is digest-verified first."""
        try:
            fd = os.open(mpath, os.O_RDWR)
        except OSError:
            return
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                # Owner alive (another replica of this model sharing
                # the dir) — its generation is not ours to touch.
                out["generations_live"] += 1
                os.close(fd)
                return
            try:
                with open(fd, "r", encoding="utf-8",
                          errors="replace", closefd=False) as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            header = None
            if lines:
                try:
                    header = json.loads(lines[0])
                except (ValueError, TypeError):
                    header = None
            if (not isinstance(header, dict)
                    or header.get("kind") != "kfs-kv-tier"):
                # Unrecognizable generation: self-delete (torn header
                # from a crash mid-create, or junk in the dir).
                out["generations_rejected"] += 1
                self._discard_generation(mpath)
                return
            if header.get("model") != self.model:
                # Another model's tier sharing the dir — not ours.
                return
            if header.get("v") != _MANIFEST_V:
                out["generations_rejected"] += 1
                out["version_skew"] += max(0, len(lines) - 1)
                self._discard_generation(mpath)
                return
            if header.get("block_bytes") != self.block_bytes:
                # Geometry changed across the restart (model config
                # edit): payloads are uninterpretable — discard.
                out["generations_rejected"] += 1
                self._discard_generation(mpath)
                return
            try:
                foreign_stride = int(header.get(
                    "slot_bytes", self.slot_bytes))
            except (TypeError, ValueError):
                foreign_stride = self.slot_bytes
            state = self._replay_records(lines[1:], out)
            if state:
                self._admit_entries(mpath, foreign_stride, state, out)
            out["generations_adopted"] += 1
            self._discard_generation(mpath)
        finally:
            try:
                os.close(fd)  # releases the flock last
            except OSError:
                pass

    @staticmethod
    def _replay_records(lines: List[str],
                        out: Dict[str, int]) -> "OrderedDict":
        """Last-writer-wins replay, keyed per chain AND per slot: a
        later put to the same slot supersedes the earlier chain (how
        evictions are represented without drop records), and a drop
        removes the chain.  Torn JSON lines (crash mid-append) and
        version-skewed records each drop only themselves."""
        state: "OrderedDict[bytes, Any]" = OrderedDict()
        slot_owner: Dict[int, bytes] = {}
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (ValueError, TypeError):
                out["torn"] += 1
                continue
            if not isinstance(rec, dict):
                out["torn"] += 1
                continue
            if rec.get("v") != _MANIFEST_V:
                out["version_skew"] += 1
                continue
            op = rec.get("op")
            try:
                if op == "put":
                    chain = bytes.fromhex(rec["chain"])
                    slot = int(rec["slot"])
                    digest = str(rec["digest"])
                    prev = slot_owner.get(slot)
                    if prev is not None and prev != chain:
                        state.pop(prev, None)
                    state.pop(chain, None)
                    state[chain] = (slot, digest)
                    slot_owner[slot] = chain
                elif op == "drop":
                    chain = bytes.fromhex(rec["chain"])
                    old = state.pop(chain, None)
                    if old is not None and \
                            slot_owner.get(old[0]) == chain:
                        slot_owner.pop(old[0], None)
                else:
                    out["torn"] += 1
            except (KeyError, ValueError, TypeError):
                out["torn"] += 1
        return state

    def _admit_entries(self, mpath: str, foreign_stride: int,
                       state: "OrderedDict",
                       out: Dict[str, int]) -> None:
        bin_path = mpath[:-len(".manifest")] + ".bin"
        if not self._contained(bin_path):
            out["truncated"] += len(state)
            return
        try:
            bf = open(bin_path, "rb")
        except OSError:
            # Payload file gone: every surviving record is unservable.
            out["truncated"] += len(state)
            return
        try:
            try:
                bin_size = os.fstat(bf.fileno()).st_size
            except OSError:
                bin_size = 0
            # Manifest order is admission order, so iterating it keeps
            # the predecessor's LRU shape: the hottest (most recently
            # put) chains land last and become our MRU.
            for chain, (slot, digest) in state.items():
                off = slot * foreign_stride
                if off + self.block_bytes > bin_size:
                    out["truncated"] += 1
                    continue
                try:
                    bf.seek(off)
                    payload = bf.read(self.block_bytes)
                except OSError:
                    out["truncated"] += 1
                    continue
                if len(payload) != self.block_bytes:
                    out["truncated"] += 1
                    continue
                if payload_digest(payload) != digest:
                    out["corrupt"] += 1
                    continue
                with self._lock:
                    if self._closed:
                        out["failed"] += 1
                        continue
                    if chain in self._index:
                        out["duplicate"] += 1
                        continue
                    if not self._free:
                        # Adoption never evicts our own live entries —
                        # the successor's working set outranks the
                        # predecessor's cold tail.
                        out["dropped_capacity"] += 1
                        continue
                    slot2 = self._free.popleft()
                    off2 = slot2 * self.slot_bytes
                    self._mm[off2:off2 + self.block_bytes] = payload
                    self._digests[chain] = digest
                    self._manifest_append_locked({
                        "op": "put", "v": _MANIFEST_V,
                        "chain": chain.hex(), "slot": slot2,
                        "digest": digest})
                    self._index[chain] = slot2
                out["adopted"] += 1
        finally:
            bf.close()
        self._publish_occupancy()

    def _discard_generation(self, mpath: str) -> None:
        """Delete one foreign generation's files (containment-checked:
        nothing outside the tier dir is ever unlinked)."""
        for path in (mpath, mpath[:-len(".manifest")] + ".bin"):
            if not self._contained(path):
                continue
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- storm pin ---------------------------------------------------------
    def _note_storm(self, blocks: int) -> None:
        now = time.monotonic()
        for _ in range(blocks):
            self._fault_times.append(now)
        recent = sum(1 for t in self._fault_times
                     if now - t <= self.storm_window_s)
        if recent <= self.storm_threshold:
            return
        recorder = self._flight_recorder
        # One pin per storm window, not one per fault in it.
        if recorder is None or \
                now - self._storm_pinned_at < self.storm_window_s:
            return
        self._storm_pinned_at = now
        recorder.record({
            "kind": "kv_tier_faultback_storm",
            "model": self.model,
            "faults_in_window": recent,
            "window_s": self.storm_window_s,
            "host_tier": self.debug(),
        }, pin="kv_faultback_storm")
        logger.warning(
            "kv tier fault-back storm: %d blocks in %.0fs (device "
            "pool churns conversations through the host tier — "
            "flight-recorder entry pinned)",
            recent, self.storm_window_s)

    # -- introspection -----------------------------------------------------
    def _publish_occupancy(self) -> None:
        with self._lock:
            used = len(self._index)
        obs.generator_kv_tier_blocks().labels(
            model=self.model).set(float(used))
        obs.generator_kv_tier_occupancy_ratio().labels(
            model=self.model).set(
                min(1.0, used / max(1, self.capacity_blocks)))

    def debug(self) -> Dict[str, Any]:
        """The `host_tier` block of `/debug/cache`, federated by the
        router under the `replica` label."""
        with self._lock:
            samples = sorted(self._fault_ms)

            def pct(q: float) -> float:
                if not samples:
                    return 0.0
                return round(samples[min(len(samples) - 1,
                                         int(len(samples) * q))], 3)

            return {
                "capacity_blocks": self.capacity_blocks,
                "used_blocks": len(self._index),
                "block_bytes": self.block_bytes,
                "slot_bytes": self.slot_bytes,
                "file_bytes": self.capacity_blocks * self.slot_bytes,
                "inflight_faults": len(self._inflight),
                "spills": self.spills,
                "spill_failures": self.spill_failures,
                "spill_duplicates": self.spill_duplicates,
                "faulted_blocks": self.faults,
                "coalesced_blocks": self.coalesced,
                "fault_failures": self.fault_failures,
                "evictions": self.evictions,
                "eviction_skips": self.eviction_skips,
                "dropped": self.dropped,
                "faultback_ms": {"p50": pct(0.50), "p99": pct(0.99)},
                "persistent": self.persistent,
                "manifest_records": self._manifest_records,
                "manifest_failures": self.manifest_failures,
                "handoff": dict(self.handoff),
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._index.clear()
            self._inflight.clear()
            self._digests.clear()
            try:
                self._mm.close()
            except Exception:
                pass
            if self._mfd is not None:
                try:
                    os.close(self._mfd)  # releases the flock
                except OSError:
                    pass
                self._mfd = None
        if self.persistent:
            # The whole point: files STAY for the successor to adopt.
            return
        try:
            os.unlink(self.path)
            if self._owns_dir:
                os.rmdir(os.path.dirname(self.path))
        except OSError:
            pass
