"""Speculative decoding support: draft proposers + the draft-model
residency handle (ROADMAP item 2, ISSUE 20).

Decode on a real model is memory-bandwidth-bound: every wave re-reads
the full parameter set to emit ONE token per slot.  Speculative
decoding amortizes that read across K+1 tokens — a cheap *proposer*
guesses K tokens per live slot, the target model scores all K+1
positions in ONE Lq>1 dispatch (the chunk-prefill cache mode +
multi-position `logit_positions`, engine/generator.py), and the engine
accepts the longest prefix on which the target's own sampled token
agrees with the proposal.

Two proposers, one contract (`propose` K tokens per slot):

- **NGramProposer** — zero-cost prompt-lookup head (host-side): find
  the longest n-gram suffix of the slot's history earlier in the
  prompt+generated stream and replay the tokens that followed it.
  Free to run, surprisingly effective on the repetitive tails real
  generation produces, and the always-available fallback when no
  draft model is configured.
- **draft model** — a small registered decoder proposing greedily via
  a jitted rolling-window scan (`make_draft_proposer`).  The window
  rides RELATIVE positions 0..W-1: draft proposals are guesses, not
  truth — the verify dispatch is the oracle, so the draft never needs
  absolute-position fidelity (and one compile serves every wave).

Parity note (why exact-match acceptance is exact for sampling too):
the engine's sampler is deterministic given (seed, absolute position)
— noise is `fold_in(fold_in(base_key, seed), pos)` (generator.py).
The target's "sample" at position p is therefore a pure function of
the prefix, and classic rejection sampling against a point-mass draft
distribution degenerates to: accept iff the proposal EQUALS the
target's draw at p, else emit the target's draw.  That is bit-exact
with non-speculative decode for greedy AND seeded sampling — a
stronger guarantee than the distributional parity general rejection
sampling gives.

`DraftModel` is the residency-manager handle (engine/residency.py
managed-model contract): the draft registers beside the target as a
second model so the HBM ledger accounts both and `kfs models` shows
it; it is PINNED (offloadable=False) while the target engine serves —
evicting the draft mid-stream would silently flip live streams onto
the slower non-speculative path.
"""

import logging
from typing import Any, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("kfserving_tpu.speculative")

# Longest n-gram the prompt-lookup head tries to match, descending to
# 1; 3 is the LLMA/prompt-lookup sweet spot — longer keys rarely
# re-occur, shorter ones mispredict.
NGRAM_MAX_N = 3
# Rolling draft window default: long enough for local coherence, small
# enough that K cache-less forwards stay a fraction of one target wave.
DEFAULT_DRAFT_WINDOW = 32


class NGramProposer:
    """Prompt-lookup proposer: propose the K tokens that followed the
    most recent earlier occurrence of the history's longest suffix
    n-gram.  Pure host-side numpy — zero device cost, zero extra HBM.
    """

    def __init__(self, k: int, max_n: int = NGRAM_MAX_N):
        self.k = int(k)
        self.max_n = int(max_n)

    def propose(self, history: Sequence[int]) -> List[int]:
        """K proposed continuation tokens for one slot.  A history
        with no repeated suffix proposes repeats of the last token —
        still a valid guess (verify rejects bad ones at zero parity
        cost; repetition is common enough that it pays for itself)."""
        hist = list(history)
        k = self.k
        n_hist = len(hist)
        fill = hist[-1] if hist else 0
        for n in range(min(self.max_n, n_hist - 1), 0, -1):
            key = hist[-n:]
            # Scan backwards for the most recent earlier occurrence —
            # recency matters: generation loops locally.
            for start in range(n_hist - n - 1, -1, -1):
                if hist[start:start + n] == key:
                    cont = hist[start + n:start + n + k]
                    if cont:
                        return (cont + [fill] * k)[:k]
        return [fill] * k


def rolling_windows(histories: Sequence[Sequence[int]], slots: int,
                    rows: Sequence[int], window: int) -> np.ndarray:
    """[slots, window] int32 draft-model input: each listed row's last
    `window` history tokens, left-padded with 0.  Unlisted rows stay
    zero — their proposals are garbage the verify dispatch parks."""
    ids = np.zeros((slots, window), np.int32)
    for row, hist in zip(rows, histories):
        tail = list(hist)[-window:]
        if tail:
            ids[row, window - len(tail):] = tail
    return ids


def make_draft_proposer(jax_mod, module, slots: int, window: int,
                        k: int):
    """Jitted greedy rolling-window proposer: (variables, ids[S, W])
    -> proposals [S, K].  Each scan step runs one cache-less full
    forward over the window, argmaxes the last position, and
    roll-appends — static shapes, one compile per (S, W, K).

    Greedy regardless of the request's sampling params: proposals are
    guesses, and exact-match acceptance guarantees parity whatever the
    proposer emits — greedy just maximizes the acceptance rate a tiny
    deterministic draft can reach."""
    jnp = jax_mod.numpy
    last_idx = jnp.full((slots,), window - 1, jnp.int32)

    def propose(variables, ids):
        def step(ids, _):
            logits = module.apply(variables, ids,
                                  logit_positions=last_idx)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            ids = jnp.concatenate([ids[:, 1:], nxt[:, None]], axis=1)
            return ids, nxt

        _, toks = jax_mod.lax.scan(step, ids, None, length=k)
        return jnp.swapaxes(toks, 0, 1)  # [S, K]

    return jax_mod.jit(propose)


class DraftModel:
    """Residency-manager handle for the draft (engine/residency.py
    managed-model contract).  The draft is a dependent of a live
    target engine, not an independently schedulable model: it
    registers as resident (ready + engine set), reports its param
    bytes for the HBM ledger, and vetoes eviction (offloadable=False)
    for as long as the target serves — the ResidencyManager's
    admission-aware eviction then never picks it as a victim."""

    def __init__(self, name: str, module: Any, variables: Any,
                 target_engine: Any, window: int = DEFAULT_DRAFT_WINDOW):
        self.name = name
        self.module = module
        self.variables = variables
        self.window = int(window)
        # Managed-model contract surface: a non-None engine + ready
        # registers the record directly in the "resident" state.
        self.engine = target_engine
        self.ready = True

    def param_bytes(self) -> int:
        import jax

        return sum(int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
                   for x in jax.tree.leaves(self.variables))

    # -- residency hooks ---------------------------------------------------
    @property
    def offloadable(self) -> bool:
        """Pinned while the target engine is live: evicting the draft
        would silently degrade every in-flight stream to
        non-speculative decode."""
        return self.engine is None

    def offload(self) -> None:
        raise RuntimeError(
            f"draft model {self.name} is pinned while its target "
            "engine serves")

    def fault_in(self) -> None:
        """Nothing to restore: draft params live wherever the target
        engine placed them (they were admitted with the target's
        load)."""

    def host_bytes(self) -> int:
        return self.param_bytes()

    def load(self) -> None:
        """Cold build is the target's job (the draft is materialized
        inside GenerativeModel.load); a standalone load is a no-op."""

    def release(self) -> None:
        """Unpin on target unload: the handle stops claiming an
        engine, so a lingering registration becomes evictable and
        `deregister` leaves no dangling veto."""
        self.engine = None
