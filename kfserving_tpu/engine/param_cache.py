"""Host-side mmap-able parameter cache: successors map, never re-init.

The r5 SOAK phase breakdown put 8-18 s of every recycle successor's
load time in `init_params` — re-materializing weights (jitted random
init + flax checkpoint deserialization, both full host copies) that an
identical predecessor process materialized seconds earlier.  The
pod-world has no answer to this (every container restart re-reads the
checkpoint); a single-host fabric does: persist the materialized
variables once, in a layout `np.memmap` can serve, and every successor
maps the SAME page-cache-resident bytes and goes straight to the
device transfer.  This is the load-fully-warm half of
TensorFlow-Serving's aspired-versions lifecycle (arxiv 1712.06139)
applied to process recycling.

Cache layout (one entry per content digest):

    <cache_dir>/<digest>/manifest.json   leaf paths, dtypes, shapes,
                                         byte offsets into params.bin
    <cache_dir>/<digest>/params.bin      all leaves, page-aligned

The digest keys the *content* that determines the materialized
variables: architecture + arch_kwargs + init seed + the checkpoint
file's digest (the artifact's shipped `*.sha256` when present, else a
full file hash).  A new checkpoint or changed config therefore misses
— invalidation is by construction, never by mtime heuristics.

Entries are written atomically (temp dir + rename), loads are
zero-copy views over one read-only memmap, and every outcome lands in
`kfserving_tpu_param_cache_total{outcome=hit|miss|store|error}`.
Knobs: `KFS_PARAM_CACHE` (directory; `0`/`off` disables).
"""

import hashlib
import json
import logging
import os
import shutil
import tempfile
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("kfserving_tpu.param_cache")

ENV_VAR = "KFS_PARAM_CACHE"
DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/kfserving_tpu/params")
MANIFEST_NAME = "manifest.json"
DATA_NAME = "params.bin"
MANIFEST_VERSION = 1
# Leaf offsets align to the page size so a mapped leaf never shares a
# page with its neighbor's tail (and device DMA gets aligned sources).
_ALIGN = 4096


def cache_dir() -> Optional[str]:
    """The active cache directory, or None when disabled."""
    value = os.environ.get(ENV_VAR, "")
    if value.lower() in ("0", "off", "false", "disabled"):
        return None
    return value or DEFAULT_CACHE_DIR


def _observe(outcome: str) -> None:
    try:
        from kfserving_tpu.observability import metrics as obs

        obs.param_cache_total().labels(outcome=outcome).inc()
    except Exception:  # telemetry must never fail a load
        logger.debug("param-cache metric emit failed", exc_info=True)


def file_digest(path: str) -> str:
    """Digest of a checkpoint file.  Prefers the artifact's shipped
    `<path>.sha256` sidecar (storage verified it at download, and
    re-hashing a multi-GB checkpoint on every boot would give back a
    slice of the very seconds this cache exists to save)."""
    sidecar = path + ".sha256"
    try:
        with open(sidecar) as f:
            token = f.read().split()[0].strip()
        if token:
            return token
    except (OSError, IndexError):
        pass
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def content_key(architecture: str, arch_kwargs: Optional[Dict],
                seed: int = 0,
                checkpoint_digest: Optional[str] = None) -> str:
    """Digest over everything that determines the materialized
    variables — two deployments agreeing on this key may share bytes."""
    blob = json.dumps({
        "architecture": architecture,
        "arch_kwargs": arch_kwargs or {},
        "seed": seed,
        "checkpoint": checkpoint_digest or "none",
        "version": MANIFEST_VERSION,
    }, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _flatten(tree: Any, prefix: Tuple[str, ...] = ()
             ) -> Iterator[Tuple[Tuple[str, ...], Any]]:
    """Depth-first (path, leaf) pairs of a nested-dict pytree.  Only
    dicts recurse: any other container is treated as a leaf, and a
    non-arrayable leaf fails the store's try (those trees are simply
    not cached — the flax variable trees this serves are plain nested
    dicts of arrays)."""
    for key in sorted(tree):
        value = tree[key]
        if isinstance(value, dict):
            yield from _flatten(value, prefix + (str(key),))
        else:
            yield prefix + (str(key),), value


def _unflatten(leaves: List[Tuple[Tuple[str, ...], Any]]) -> Dict:
    tree: Dict = {}
    for path, leaf in leaves:
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = leaf
    return tree


def _resolve_dtype(name: str) -> np.dtype:
    """numpy dtype by name, falling through to ml_dtypes for the
    accelerator types numpy doesn't know (bfloat16, float8_*)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def store(key: str, variables: Any) -> bool:
    """Persist a materialized variable tree under `key`.  Best-effort:
    returns False (and counts `error`) on any failure — a broken cache
    write must never take down a load that already succeeded."""
    root = cache_dir()
    if root is None or not isinstance(variables, dict):
        return False
    entry = os.path.join(root, key)
    if os.path.exists(os.path.join(entry, MANIFEST_NAME)):
        return True  # a concurrent successor already wrote it
    try:
        leaves = list(_flatten(variables))
        manifest: List[Dict[str, Any]] = []
        offset = 0
        arrays = []
        for path, leaf in leaves:
            arr = np.ascontiguousarray(np.asarray(leaf))
            offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
            manifest.append({
                "path": list(path),
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "offset": offset,
                "nbytes": int(arr.nbytes),
            })
            arrays.append((offset, arr))
            offset += arr.nbytes
        os.makedirs(root, exist_ok=True)
        tmp = tempfile.mkdtemp(prefix=f".{key}-", dir=root)
        try:
            with open(os.path.join(tmp, DATA_NAME), "wb") as f:
                for off, arr in arrays:
                    f.seek(off)
                    f.write(arr.tobytes())
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump({"version": MANIFEST_VERSION,
                           "total_bytes": offset,
                           "leaves": manifest}, f)
            # Atomic publish: readers see either nothing or a complete
            # entry (rename fails if a racing writer won — their entry
            # is byte-identical, so losing is fine).
            try:
                os.rename(tmp, entry)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    except Exception:
        logger.warning("param-cache store of %s failed", key,
                       exc_info=True)
        _observe("error")
        return False
    _observe("store")
    logger.info("param cache stored %s (%d leaves, %.1f MB)",
                key, len(manifest), offset / 1e6)
    return True


def load(key: str) -> Optional[Dict]:
    """Map a cached variable tree: one read-only memmap of params.bin,
    every leaf a zero-copy view into it.  None on miss or any
    corruption (a corrupt entry is deleted so the next boot re-stores
    it)."""
    root = cache_dir()
    if root is None:
        return None
    entry = os.path.join(root, key)
    manifest_path = os.path.join(entry, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        _observe("miss")
        return None
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        if manifest.get("version") != MANIFEST_VERSION:
            raise ValueError(
                f"manifest version {manifest.get('version')}")
        data = np.memmap(os.path.join(entry, DATA_NAME),
                         dtype=np.uint8, mode="r")
        leaves = []
        for leaf in manifest["leaves"]:
            off, nbytes = leaf["offset"], leaf["nbytes"]
            if off + nbytes > data.size:
                raise ValueError(
                    f"leaf {leaf['path']} overruns params.bin")
            arr = (np.asarray(data[off:off + nbytes])
                   .view(_resolve_dtype(leaf["dtype"]))
                   .reshape(leaf["shape"]))
            leaves.append((tuple(leaf["path"]), arr))
    except Exception:
        logger.warning("param cache entry %s is corrupt; deleting",
                       key, exc_info=True)
        shutil.rmtree(entry, ignore_errors=True)
        _observe("error")
        return None
    _observe("hit")
    logger.info("param cache hit %s (%d leaves, %.1f MB mapped)",
                key, len(leaves), manifest["total_bytes"] / 1e6)
    return _unflatten(leaves)


def load_or_materialize(architecture: str, arch_kwargs: Optional[Dict],
                        spec, local_dir: str,
                        checkpoint_name: str = "checkpoint.msgpack",
                        seed: int = 0) -> Tuple[Dict, str]:
    """The shared predictor load path: (variables, source) where source
    is "mmap" (cache hit — successor skipped materialization
    entirely), "checkpoint" (init + restore, then stored), or "init"
    (random weights, then stored).

    On a hit the arrays are read-only memmap views; jit/device_put
    consume them directly, so the host cost of a successor's param
    phase collapses to page-cache reads feeding the device transfer.
    """
    from kfserving_tpu import startup
    from kfserving_tpu.models import init_params

    ckpt_path = os.path.join(local_dir, checkpoint_name)
    ckpt_digest = (file_digest(ckpt_path)
                   if os.path.exists(ckpt_path) else None)
    key = content_key(architecture, arch_kwargs, seed=seed,
                      checkpoint_digest=ckpt_digest)
    cached = load(key)
    if cached is not None:
        startup.mark("params_mmap")
        return cached, "mmap"
    variables = init_params(spec, seed=seed)
    startup.mark("init_params")
    source = "init"
    if ckpt_digest is not None:
        from flax import serialization

        with open(ckpt_path, "rb") as f:
            variables = serialization.from_bytes(variables, f.read())
        logger.info("restored checkpoint %s", ckpt_path)
        startup.mark("checkpoint_restore")
        source = "checkpoint"
    else:
        logger.warning("no checkpoint at %s; serving random init",
                       ckpt_path)
    # Jax arrays (init output) convert to host np arrays inside
    # store().  After a successful store, serve the MAPPED bytes we
    # just wrote rather than the in-process copies: the residency
    # manager needs a host-side (mmap) restore source to demand-page
    # this model in and out of HBM, and the page cache shares the
    # bytes with every successor.  A failed re-load (racing writer,
    # disabled cache) falls back to the in-process copies — the load
    # itself must never depend on the cache.
    if isinstance(variables, dict) and store(key, variables):
        startup.mark("param_cache_store")
        mapped = load(key)
        if mapped is not None:
            return mapped, source
    return variables, source
