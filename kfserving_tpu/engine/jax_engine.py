"""JaxEngine: the TPU execution runtime behind a served model.

The reference has no counterpart — it delegates accelerator execution to
third-party servers (TFServing/Triton; SURVEY.md §7.2).  This engine is the
new native heart:

- one jit-compiled executable per (batch-bucket, extra dynamic dims) shape,
  compiled against params already resident in HBM;
- requests are padded up to the nearest bucket and sliced back after;
- execution runs in a worker thread so the asyncio serving loop never blocks
  on device latency (`jax.block_until_ready` happens off-loop);
- optional sharded execution: params placed with a NamedSharding over a
  device mesh make every bucketed executable an SPMD program over ICI
  (tensor parallelism for models larger than one chip);
- warmup() pre-compiles all buckets so readiness gating can include compile
  time (SURVEY.md §5.3 cold-start mitigation), complementing the persistent
  XLA compilation cache (engine/compile_cache.py).
"""

import asyncio
import concurrent.futures
import contextvars
import itertools
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from kfserving_tpu.engine import compile_cache
from kfserving_tpu.engine.buckets import BucketPolicy
from kfserving_tpu.observability.profiling import TIMELINE
from kfserving_tpu.reliability import sanitizer

logger = logging.getLogger("kfserving_tpu.engine")

# Monotonic engine ids for the sanitizer's recompile assertion:
# id(self) would recycle addresses across engine unload/load, making
# a fresh engine inherit its predecessor's warmup declaration.
_engine_seq = itertools.count()


def device_peak_flops() -> Optional[float]:
    """Peak dense-matmul FLOP/s of the serving chip (bf16), for MFU.

    Override with KFS_PEAK_FLOPS.  Returns None when unknown (CPU
    backend) — stats then omit the MFU line rather than fake it.
    """
    env = os.getenv("KFS_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for marker, peak in (("v5 lite", 197e12), ("v5e", 197e12),
                        ("v5p", 459e12), ("v6", 918e12),
                        ("v4", 275e12), ("v3", 123e12), ("v2", 45e12)):
        if marker in kind:
            return peak
    return None


def _params_on_single_device(jax, params) -> bool:
    """True when every param leaf lives on one device — then the engine
    issues an explicit async device_put so batch N+1's host->HBM
    transfer overlaps batch N's compute.  Mesh-sharded params skip the
    explicit put: jit handles SPMD placement."""
    try:
        for leaf in jax.tree.leaves(params):
            sharding = getattr(leaf, "sharding", None)
            device_set = getattr(sharding, "device_set", None)
            if device_set is not None and len(device_set) > 1:
                return False
        return True
    except Exception:
        return False


def _all_host_leaves(jax, params) -> bool:
    """True when every param leaf is a plain host ndarray (the
    mmap-view trees param_cache serves) — the precondition for
    offload()/restore() keeping a zero-copy restore source."""
    try:
        leaves = jax.tree.leaves(params)
        return bool(leaves) and all(
            isinstance(leaf, np.ndarray) for leaf in leaves)
    except Exception:
        return False


def _resize_seq(arr: np.ndarray, seq: int) -> np.ndarray:
    """Clip or tile a single instance's leading (sequence) axis to `seq`
    for warmup shape synthesis."""
    if arr.ndim == 0 or arr.shape[0] == seq:
        return arr
    if arr.shape[0] > seq:
        return arr[:seq]
    reps = (seq + arr.shape[0] - 1) // arr.shape[0]
    return np.concatenate([arr] * reps, axis=0)[:seq]


class JaxEngine:
    """Bucketed, padded, jit-compiled batch execution of `apply_fn(params, x)`.

    apply_fn: a traceable function of (params, batch_array) or
        (params, dict_of_batch_arrays) returning an array / pytree whose
        leading axis is the batch dimension.
    params: model parameters (pytree of jax arrays), already device_put
        (possibly with NamedSharding for multi-chip).
    batch_buckets: BucketPolicy for the leading batch dimension.
    seq_buckets: optional BucketPolicy for axis 1 (sequence length) — used by
        text models; images have static trailing dims.
    """

    def __init__(self, apply_fn: Callable, params: Any,
                 batch_buckets: Optional[BucketPolicy] = None,
                 seq_buckets: Optional[BucketPolicy] = None,
                 dtype: Optional[Any] = None,
                 pad_value: float = 0.0,
                 donate_inputs: bool = False,
                 pipeline_depth: int = 2,
                 blocking_stats: Optional[bool] = None,
                 param_source: Optional[str] = None):
        import jax

        self._jax = jax
        self.params = params
        # Host-side restore source for demand-paged residency
        # (engine/residency.py): when the param tree is entirely host
        # arrays (the mmap-backed views param_cache.load serves), keep
        # the reference — offload() can then drop the device copies and
        # restore() re-place them with one device_put, no re-
        # materialization and no recompile (jit caches by shape/dtype,
        # which a restore never changes).  Mesh-sharded trees are not
        # offloadable (jit owns their SPMD placement).
        self._host_params = (params if _all_host_leaves(jax, params)
                             else None)
        self.batch_buckets = batch_buckets or BucketPolicy.pow2(32)
        self.seq_buckets = seq_buckets
        self.dtype = dtype
        self.pad_value = pad_value
        # jax.jit caches one executable per padded shape signature; the
        # bucket policies bound how many signatures can exist.
        donate = (1,) if donate_inputs else ()
        self._jitted = jax.jit(apply_fn, donate_argnums=donate)
        # pipeline_depth worker threads: device execution is serialized per
        # chip, but the host->HBM transfer of batch N+1 overlaps the compute
        # and result fetch of batch N (transfers dominate when the chip sits
        # across a PCIe/tunnel hop).  Depth 2 = classic double buffering.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, pipeline_depth),
            thread_name_prefix="jax-engine")
        # Telemetry (lock: _execute_sync runs on pipeline_depth threads)
        import threading

        self._stats_lock = threading.Lock()
        self.compile_count = 0
        self.execute_count = 0
        self.last_execute_ms = 0.0
        self.padded_waste_total = 0.0
        # Device-vs-host breakdown (VERDICT r1 #3): where a request's
        # milliseconds actually go, and achieved FLOP/s vs chip peak.
        self.prepare_ms_total = 0.0   # host: pad/stack/dtype
        self.device_ms_total = 0.0    # dispatch -> block_until_ready
        self.fetch_ms_total = 0.0     # device -> host slice
        self.flops_total = 0.0
        self._flops_by_bucket: Dict[Any, float] = {}
        # Per-(batch,seq)-bucket execution counts + padded-slot waste:
        # which compiled programs traffic actually lands on (seq-bucket
        # coverage is a bench deliverable, BASELINE config #3).
        self._bucket_hits: Dict[Any, int] = {}
        self._bucket_waste: Dict[Any, float] = {}
        self._slots_total = 0
        self._padded_slots_total = 0
        # Shapes this engine has dispatched before: the first dispatch
        # per (batch, seq) bucket pays jit trace+compile (a persistent-
        # XLA-cache hit still costs a load), later ones are cache hits
        # — the compile-cache counter series feeds off this.
        self._compiled_shapes: set = set()
        self._explicit_transfer = _params_on_single_device(jax, params)
        self._peak_flops = device_peak_flops()
        # One host<->device synchronization per batch, not two: the result
        # fetch (np.asarray) already waits for completion, and an explicit
        # block_until_ready first costs a *second* runtime round trip —
        # measured 433ms vs 103ms per batch on a tunneled v5e chip.  The
        # block is only worth paying when attributing device-vs-fetch time
        # (KFS_ENGINE_BLOCKING_STATS=1 or blocking_stats=True).
        if blocking_stats is None:
            blocking_stats = os.getenv(
                "KFS_ENGINE_BLOCKING_STATS", "") not in ("", "0", "false")
        self._blocking_stats = blocking_stats
        self.pipeline_depth = max(1, pipeline_depth)
        # Param provenance ("mmap" | "checkpoint" | "init" | None):
        # lets a scrape tell a mapped-warm successor from a replica
        # that paid full materialization — the lifecycle SOAK's
        # per-replica evidence that the mmap cache actually engaged.
        self.param_source = param_source
        # Identity for the KFS_SANITIZE recompile assertion: each
        # engine declares its own warmup, so one engine warming never
        # flags another engine serving.  Process-monotonic (never an
        # address): a recycled id would hand a fresh engine its
        # predecessor's warmup declaration.
        self.sanitize_source = f"jax_engine:{next(_engine_seq)}"

    # -- shape plumbing ------------------------------------------------------
    def _pad_to_bucket(self, arr: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pad leading (and optionally seq) dims to bucket sizes."""
        n = arr.shape[0]
        b = self.batch_buckets.fit(n)
        if b is None:
            raise ValueError(
                f"batch of {n} exceeds the largest compiled bucket "
                f"{self.batch_buckets.max}")
        pad = [(0, b - n)] + [(0, 0)] * (arr.ndim - 1)
        if self.seq_buckets is not None and arr.ndim >= 2:
            s = self.seq_buckets.fit(arr.shape[1])
            if s is None:
                raise ValueError(
                    f"sequence length {arr.shape[1]} exceeds the largest "
                    f"bucket {self.seq_buckets.max}")
            pad[1] = (0, s - arr.shape[1])
        if any(p[1] for p in pad):
            arr = np.pad(arr, pad, constant_values=self.pad_value)
        return arr, n

    def _prepare(self, inputs: Any) -> Tuple[Any, int]:
        if isinstance(inputs, dict):
            padded = {}
            n = None
            for k, v in inputs.items():
                arr = np.asarray(v)
                if self.dtype is not None and np.issubdtype(
                        arr.dtype, np.floating):
                    arr = arr.astype(self.dtype)
                p, n_k = self._pad_to_bucket(arr)
                padded[k] = p
                if n is None:
                    n = n_k
                elif n != n_k:
                    raise ValueError("inconsistent batch sizes across inputs")
            return padded, int(n)
        arr = np.asarray(inputs)
        if self.dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(self.dtype)
        return self._pad_to_bucket(arr)

    # -- execution -----------------------------------------------------------
    def _execute_sync(self, inputs: Any) -> Any:
        from kfserving_tpu.reliability.deadline import check_deadline
        from kfserving_tpu.tracing import tracer

        # Last stop before device work: the caller's context (and so
        # its deadline) rode into this worker thread via ctx.run — an
        # over-budget request fails 504 here instead of occupying the
        # chip.  Batched executions carry no ambient deadline (the
        # batcher clears it; per-request budgets were settled at the
        # queue edge).
        check_deadline("engine dispatch")
        if self.params is None:
            # Offloaded by the residency manager and not faulted back
            # in: fail loudly — a half-loaded model must never serve
            # (the predict path's ensure_resident() gate is the only
            # legitimate way back to device residency).
            raise RuntimeError(
                "engine params are offloaded from the device "
                "(model is not HBM-resident)")
        with tracer.span("engine.execute") as span:
            t0 = time.perf_counter()
            padded, n = self._prepare(inputs)
            # A bucket warmup never visited (minimal-warmup recycle
            # successors warm only the largest) still records its cost
            # model on first execution — otherwise flops_total/MFU
            # silently collapse on exactly those replicas.
            if self._flops_key(padded) not in self._flops_by_bucket:
                self._record_flops(
                    padded.shape[0] if hasattr(padded, "shape")
                    else len(next(iter(padded.values()))), padded)
            t1 = time.perf_counter()
            if self._explicit_transfer:
                # Async H2D dispatch: with pipeline_depth worker threads,
                # this thread's transfer overlaps another thread's
                # in-flight compute (double buffering across the PCIe /
                # tunnel hop).
                padded = self._jax.device_put(padded)
            t_transfer = time.perf_counter()
            out = self._jitted(self.params, padded)
            if self._blocking_stats:
                # Attribution mode: pay the extra sync so device_ms is
                # pure device time and fetch_ms pure D2H.
                out = self._jax.block_until_ready(out)
            t2 = time.perf_counter()
            # THE sanctioned result fetch: this executor thread is
            # where device results become host arrays by design.
            with sanitizer.sanctioned_fetch():
                result = self._jax.tree.map(
                    # kfslint: disable=host-sync — sanctioned fetch
                    # site: the engine's one D2H join, worker thread.
                    lambda a: np.asarray(a)[:n], out)
            t3 = time.perf_counter()
            first = (padded[next(iter(padded))]
                     if isinstance(padded, dict) else padded)
            bucket = first.shape[0]
            flops_key = self._flops_key(padded)
            span.update(batch=n, bucket=int(bucket),
                        prepare_ms=round((t1 - t0) * 1e3, 3),
                        device_ms=round((t2 - t1) * 1e3, 3),
                        fetch_ms=round((t3 - t2) * 1e3, 3))
            # Stage histograms, exemplared with the request's trace id
            # (the contextvar rode into this worker thread): the
            # fleet-wide view of where a request's milliseconds go.
            from kfserving_tpu.observability import metrics as obs
            from kfserving_tpu.tracing import current_request_id

            trace_id = current_request_id.get()
            stage_hist = obs.engine_stage_ms()
            for stage, ms in (("prepare", (t1 - t0) * 1e3),
                              ("transfer", (t_transfer - t1) * 1e3),
                              ("compute", (t2 - t_transfer) * 1e3),
                              ("fetch", (t3 - t2) * 1e3)):
                stage_hist.labels(stage=stage).observe(
                    ms, trace_id=trace_id)
            # Device-dispatch slice on the engine event timeline: the
            # dispatch -> host-visible-result span (pure device time
            # only under blocking_stats; otherwise it includes the
            # runtime round trip — same caveat as device_ms).
            TIMELINE.record("device", "engine.execute",
                            dur_s=t3 - t1, trace_id=trace_id,
                            attrs={"bucket": int(bucket), "batch": n})
            first_dispatch = False
            with self._stats_lock:
                if flops_key not in self._compiled_shapes:
                    self._compiled_shapes.add(flops_key)
                    first_dispatch = True
                    obs.compile_cache_events().labels(
                        outcome="miss").inc()
                    TIMELINE.record(
                        "host", "compile.miss", trace_id=trace_id,
                        attrs={"shape": str(flops_key)})
                else:
                    obs.compile_cache_events().labels(
                        outcome="hit").inc()
                # dispatch -> host-visible result (full device path)
                self.last_execute_ms = (t3 - t1) * 1000.0
                self.execute_count += 1
                self.padded_waste_total += (bucket - n) / bucket
                self.prepare_ms_total += (t1 - t0) * 1e3
                self.device_ms_total += (t2 - t1) * 1e3
                self.fetch_ms_total += (t3 - t2) * 1e3
                self.flops_total += self._flops_by_bucket.get(
                    flops_key, 0.0)
                self._bucket_hits[flops_key] = \
                    self._bucket_hits.get(flops_key, 0) + 1
                self._bucket_waste[flops_key] = \
                    self._bucket_waste.get(flops_key, 0.0) \
                    + (bucket - n) / bucket
                self._slots_total += bucket
                self._padded_slots_total += bucket - n
            if first_dispatch:
                # Sanitizer feed, OUTSIDE the stats lock: a recompile
                # violation's counter+pin work must not convoy the
                # other executor workers behind telemetry.
                compile_cache.note_compilation(self.sanitize_source,
                                               flops_key)
        return result

    async def predict(self, inputs: Any) -> Any:
        """Async batch predict: pads, executes on device off-loop, unpads.

        The caller's context (request-id contextvar) rides into the
        worker thread so engine spans attach to the request's trace.
        """
        loop = asyncio.get_running_loop()
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(
            self._executor, ctx.run, self._execute_sync, inputs)

    def predict_sync(self, inputs: Any) -> Any:
        return self._execute_sync(inputs)

    # -- lifecycle -----------------------------------------------------------
    def warmup(self, example: Any, buckets: Optional[List[int]] = None,
               minimal: bool = False) -> float:
        """Pre-compile every executable a request can hit: all batch
        buckets x all seq buckets (sequence models without the full grid
        warm compile at serve time instead — measured ~25s per shape on
        a tunneled chip, which turns first requests into timeouts).
        Returns total compile seconds.  `example` is a single instance
        (no batch dim) as array or dict of arrays.

        minimal=True warms only the LARGEST batch bucket per seq
        bucket — the recycle-successor mode: the predecessor populated
        the persistent compile cache, so the remaining programs load
        on demand in sub-seconds, and the full grid's ~RTT-per-program
        dispatch tax was the dominant term of successor load time
        (measured r5 SOAK: warmup was 11 of a warm successor's 21 s)."""
        start = time.perf_counter()
        batch_buckets = buckets or self.batch_buckets.buckets
        if minimal:
            batch_buckets = [max(batch_buckets)]
        seq_buckets = (self.seq_buckets.buckets
                       if self.seq_buckets is not None else [None])

        def instance_at(seq):
            if seq is None:
                return example
            if isinstance(example, dict):
                return {k: _resize_seq(np.asarray(v), seq)
                        for k, v in example.items()}
            return _resize_seq(np.asarray(example), seq)

        for s in seq_buckets:
            inst = instance_at(s)
            for b in batch_buckets:
                if isinstance(inst, dict):
                    batch = {k: np.stack([np.asarray(v)] * b)
                             for k, v in inst.items()}
                else:
                    batch = np.stack([np.asarray(inst)] * b)
                self._execute_sync(batch)
                self.compile_count += 1
                self._record_flops(b, batch)
        dt = time.perf_counter() - start
        # Full-grid warmup closes this engine's shape set: arm the
        # sanitizer's recompile assertion.  A minimal warmup
        # deliberately leaves programs to load on demand — those
        # late loads are the chosen trade, not violations, so the
        # source stays unarmed.
        if not minimal:
            compile_cache.declare_warmup_complete(
                self.sanitize_source)
        # Warmup executes exactly-full batches of every program; leaving
        # them in the traffic counters would report phantom bucket hits
        # and dilute slot_pad_waste toward 0 on short runs.  Timing /
        # MFU totals keep warmup (pre-existing semantics); the
        # batching-quality counters restart at zero.
        with self._stats_lock:
            self._bucket_hits.clear()
            self._bucket_waste.clear()
            self._slots_total = 0
            self._padded_slots_total = 0
        logger.info("warmup compiled %d batch x %d seq buckets in %.1fs",
                    len(batch_buckets), len(seq_buckets), dt)
        return dt

    def _flops_key(self, batch: Any):
        """Stats key: (batch bucket, seq bucket) — per-seq-bucket
        programs have different FLOPs and must not share an entry.
        Shape access only (never np.asarray: the batch may already live
        on device and a copy here would be a hidden D2H transfer)."""
        first = (batch[next(iter(batch))]
                 if isinstance(batch, dict) else batch)
        return (int(first.shape[0]),
                int(first.shape[1]) if self.seq_buckets is not None
                and getattr(first, "ndim", 0) >= 2 else None)

    def _record_flops(self, bucket: int, batch: Any) -> None:
        """XLA's cost model for this bucket's program (feeds the
        achieved-FLOP/s / MFU stats).  The lowered module's analysis is
        free but unavailable on some backends (returns None on tunneled
        TPU); fall back to the compiled executable's analysis — warmup
        already populated the jit + persistent XLA caches for this
        shape, so the extra compile() is a cache hit."""
        try:
            lowered = self._jitted.lower(self.params, batch)
            analysis = lowered.cost_analysis()
            if not analysis:
                analysis = lowered.compile().cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            flops = float((analysis or {}).get("flops", 0.0))
            if flops > 0:
                self._flops_by_bucket[self._flops_key(batch)] = flops
        except Exception as exc:  # cost model optional, never fatal
            logger.debug("cost_analysis unavailable: %s", exc)

    def param_bytes(self) -> int:
        """Total parameter bytes (HBM residency of this model's weights)."""
        leaves = self._jax.tree.leaves(self.params)
        return sum(getattr(x, "nbytes", 0) for x in leaves)

    def host_param_bytes(self) -> int:
        """Bytes the host-resident restore source would occupy in HBM
        (0 when this engine keeps no host tree — not offloadable)."""
        if self._host_params is None:
            return 0
        return sum(leaf.nbytes
                   for leaf in self._jax.tree.leaves(self._host_params))

    @property
    def offloadable(self) -> bool:
        return self._host_params is not None

    def offload(self) -> bool:
        """Drop the device param copies; the host (mmap-backed) tree
        stays as the restore source.  Returns False when this engine
        keeps no host tree (mesh-sharded params — never a residency
        victim).  The caller (residency manager) guarantees no
        execution is queued or in flight; a straggler that slips past
        fails fast on the params-None guard instead of dereferencing
        freed HBM."""
        if self._host_params is None:
            return False
        params, self.params = self.params, None
        if params is not None and params is not self._host_params:
            for leaf in self._jax.tree.leaves(params):
                delete = getattr(leaf, "delete", None)
                if delete is not None:
                    try:
                        delete()
                    except Exception:  # already deleted / host array
                        pass
        return True

    def restore(self) -> float:
        """Fault the params back into HBM off the host tree: one
        device_put of zero-copy mmap views, synchronized so the
        returned seconds are the true transfer cost.  No recompile —
        the jit cache keys on shapes/dtypes, which a restore never
        changes."""
        if self._host_params is None:
            raise RuntimeError(
                "engine keeps no host params to restore from")
        t0 = time.perf_counter()
        params = self._jax.device_put(self._host_params)
        params = self._jax.block_until_ready(params)
        self.params = params
        return time.perf_counter() - t0

    def close(self, wait: bool = True):
        """Release device references so HBM can be reclaimed.

        wait=True (default) quiesces first: in-flight executions on the
        worker threads finish before param buffers are deleted, so a
        concurrent predict never dereferences freed HBM.  Executions
        submitted after close() fail fast with RuntimeError (executor shut
        down) instead of touching deleted buffers.
        """
        self._executor.shutdown(wait=wait)
        for leaf in self._jax.tree.leaves(self.params):
            if hasattr(leaf, "delete"):
                try:
                    leaf.delete()
                except Exception:  # already deleted / cpu array
                    pass
        self.params = None

    def stats(self) -> Dict[str, float]:
        with self._stats_lock:
            n = self.execute_count
            out = {
                "execute_count": n,
                "compile_count": self.compile_count,
                "pipeline_depth": self.pipeline_depth,
                "last_execute_ms": self.last_execute_ms,
                "avg_pad_waste": (self.padded_waste_total / n
                                  if n else 0.0),
                # Slot-weighted companion: fraction of executed batch
                # SLOTS that were padding.  The unweighted mean above
                # over-counts small deadline flushes (a half-empty b4
                # and a half-empty b128 average the same there).
                "slot_pad_waste": (
                    self._padded_slots_total / self._slots_total
                    if self._slots_total else 0.0),
                "avg_prepare_ms": self.prepare_ms_total / n if n else 0.0,
                "avg_device_ms": self.device_ms_total / n if n else 0.0,
                "avg_fetch_ms": self.fetch_ms_total / n if n else 0.0,
                "blocking_stats": self._blocking_stats,
            }
            if self.param_source is not None:
                out["param_source"] = self.param_source
            # In the default non-blocking mode device_ms is just async
            # dispatch; device work completes inside the fetch wait, so
            # MFU divides by their sum (a floor on true utilization —
            # the sum includes the runtime round trip).
            device_s = (self.device_ms_total
                        if self._blocking_stats
                        else self.device_ms_total
                        + self.fetch_ms_total) / 1e3
            if self.flops_total > 0 and device_s > 0:
                achieved = self.flops_total / device_s
                out["achieved_tflops"] = achieved / 1e12
                if self._peak_flops:
                    out["mfu"] = achieved / self._peak_flops
            if self._bucket_hits:
                out["bucket_hits"] = {
                    (f"b{b}" if s is None else f"b{b}s{s}"): hits
                    for (b, s), hits in sorted(self._bucket_hits.items())}
                out["bucket_pad_waste"] = {
                    (f"b{b}" if s is None else f"b{b}s{s}"):
                        round(waste / self._bucket_hits[key], 4)
                    for key, waste in sorted(self._bucket_waste.items())
                    for b, s in [key]}
        return out
