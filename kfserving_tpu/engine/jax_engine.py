"""JaxEngine: the TPU execution runtime behind a served model.

The reference has no counterpart — it delegates accelerator execution to
third-party servers (TFServing/Triton; SURVEY.md §7.2).  This engine is the
new native heart:

- one jit-compiled executable per (batch-bucket, extra dynamic dims) shape,
  compiled against params already resident in HBM;
- requests are padded up to the nearest bucket and sliced back after;
- execution runs in a worker thread so the asyncio serving loop never blocks
  on device latency (`jax.block_until_ready` happens off-loop);
- optional sharded execution: params placed with a NamedSharding over a
  device mesh make every bucketed executable an SPMD program over ICI
  (tensor parallelism for models larger than one chip);
- warmup() pre-compiles all buckets so readiness gating can include compile
  time (SURVEY.md §5.3 cold-start mitigation), complementing the persistent
  XLA compilation cache (engine/compile_cache.py).
"""

import asyncio
import concurrent.futures
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from kfserving_tpu.engine.buckets import BucketPolicy

logger = logging.getLogger("kfserving_tpu.engine")


class JaxEngine:
    """Bucketed, padded, jit-compiled batch execution of `apply_fn(params, x)`.

    apply_fn: a traceable function of (params, batch_array) or
        (params, dict_of_batch_arrays) returning an array / pytree whose
        leading axis is the batch dimension.
    params: model parameters (pytree of jax arrays), already device_put
        (possibly with NamedSharding for multi-chip).
    batch_buckets: BucketPolicy for the leading batch dimension.
    seq_buckets: optional BucketPolicy for axis 1 (sequence length) — used by
        text models; images have static trailing dims.
    """

    def __init__(self, apply_fn: Callable, params: Any,
                 batch_buckets: Optional[BucketPolicy] = None,
                 seq_buckets: Optional[BucketPolicy] = None,
                 dtype: Optional[Any] = None,
                 pad_value: float = 0.0,
                 donate_inputs: bool = False,
                 pipeline_depth: int = 2):
        import jax

        self._jax = jax
        self.params = params
        self.batch_buckets = batch_buckets or BucketPolicy.pow2(32)
        self.seq_buckets = seq_buckets
        self.dtype = dtype
        self.pad_value = pad_value
        # jax.jit caches one executable per padded shape signature; the
        # bucket policies bound how many signatures can exist.
        donate = (1,) if donate_inputs else ()
        self._jitted = jax.jit(apply_fn, donate_argnums=donate)
        # pipeline_depth worker threads: device execution is serialized per
        # chip, but the host->HBM transfer of batch N+1 overlaps the compute
        # and result fetch of batch N (transfers dominate when the chip sits
        # across a PCIe/tunnel hop).  Depth 2 = classic double buffering.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, pipeline_depth),
            thread_name_prefix="jax-engine")
        # Telemetry (lock: _execute_sync runs on pipeline_depth threads)
        import threading

        self._stats_lock = threading.Lock()
        self.compile_count = 0
        self.execute_count = 0
        self.last_execute_ms = 0.0
        self.padded_waste_total = 0.0

    # -- shape plumbing ------------------------------------------------------
    def _pad_to_bucket(self, arr: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pad leading (and optionally seq) dims to bucket sizes."""
        n = arr.shape[0]
        b = self.batch_buckets.fit(n)
        if b is None:
            raise ValueError(
                f"batch of {n} exceeds the largest compiled bucket "
                f"{self.batch_buckets.max}")
        pad = [(0, b - n)] + [(0, 0)] * (arr.ndim - 1)
        if self.seq_buckets is not None and arr.ndim >= 2:
            s = self.seq_buckets.fit(arr.shape[1])
            if s is None:
                raise ValueError(
                    f"sequence length {arr.shape[1]} exceeds the largest "
                    f"bucket {self.seq_buckets.max}")
            pad[1] = (0, s - arr.shape[1])
        if any(p[1] for p in pad):
            arr = np.pad(arr, pad, constant_values=self.pad_value)
        return arr, n

    def _prepare(self, inputs: Any) -> Tuple[Any, int]:
        if isinstance(inputs, dict):
            padded = {}
            n = None
            for k, v in inputs.items():
                arr = np.asarray(v)
                if self.dtype is not None and np.issubdtype(
                        arr.dtype, np.floating):
                    arr = arr.astype(self.dtype)
                p, n_k = self._pad_to_bucket(arr)
                padded[k] = p
                if n is None:
                    n = n_k
                elif n != n_k:
                    raise ValueError("inconsistent batch sizes across inputs")
            return padded, int(n)
        arr = np.asarray(inputs)
        if self.dtype is not None and np.issubdtype(arr.dtype, np.floating):
            arr = arr.astype(self.dtype)
        return self._pad_to_bucket(arr)

    # -- execution -----------------------------------------------------------
    def _execute_sync(self, inputs: Any) -> Any:
        padded, n = self._prepare(inputs)
        start = time.perf_counter()
        out = self._jitted(self.params, padded)
        out = self._jax.block_until_ready(out)
        bucket = (padded[next(iter(padded))] if isinstance(padded, dict)
                  else padded).shape[0]
        with self._stats_lock:
            self.last_execute_ms = (time.perf_counter() - start) * 1000.0
            self.execute_count += 1
            self.padded_waste_total += (bucket - n) / bucket
        # Slice back to the true batch size on host.
        return self._jax.tree.map(lambda a: np.asarray(a)[:n], out)

    async def predict(self, inputs: Any) -> Any:
        """Async batch predict: pads, executes on device off-loop, unpads."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, self._execute_sync, inputs)

    def predict_sync(self, inputs: Any) -> Any:
        return self._execute_sync(inputs)

    # -- lifecycle -----------------------------------------------------------
    def warmup(self, example: Any, buckets: Optional[List[int]] = None) -> float:
        """Pre-compile executables for all batch buckets (and the example's
        seq bucket).  Returns total compile seconds.  `example` is a single
        instance (no batch dim) as array or dict of arrays."""
        start = time.perf_counter()
        for b in (buckets or self.batch_buckets.buckets):
            if isinstance(example, dict):
                batch = {k: np.stack([np.asarray(v)] * b) for k, v in
                         example.items()}
            else:
                batch = np.stack([np.asarray(example)] * b)
            self._execute_sync(batch)
            self.compile_count += 1
        dt = time.perf_counter() - start
        logger.info("warmup compiled %d buckets in %.1fs",
                    len(buckets or self.batch_buckets.buckets), dt)
        return dt

    def param_bytes(self) -> int:
        """Total parameter bytes (HBM residency of this model's weights)."""
        leaves = self._jax.tree.leaves(self.params)
        return sum(getattr(x, "nbytes", 0) for x in leaves)

    def close(self, wait: bool = True):
        """Release device references so HBM can be reclaimed.

        wait=True (default) quiesces first: in-flight executions on the
        worker threads finish before param buffers are deleted, so a
        concurrent predict never dereferences freed HBM.  Executions
        submitted after close() fail fast with RuntimeError (executor shut
        down) instead of touching deleted buffers.
        """
        self._executor.shutdown(wait=wait)
        for leaf in self._jax.tree.leaves(self.params):
            if hasattr(leaf, "delete"):
                try:
                    leaf.delete()
                except Exception:  # already deleted / cpu array
                    pass
        self.params = None

    def stats(self) -> Dict[str, float]:
        return {
            "execute_count": self.execute_count,
            "compile_count": self.compile_count,
            "last_execute_ms": self.last_execute_ms,
            "avg_pad_waste": (self.padded_waste_total / self.execute_count
                              if self.execute_count else 0.0),
        }
