"""GenerationEngine: KV-cache incremental decoding with continuous
batching.

The reference has no generative serving at all — models are opaque
request/response artifacts (reference pkg/apis/serving/v1beta1/
predictor.go:33-59) and its batcher coalesces whole requests
(pkg/batcher/handler.go:129-150).  Token generation breaks that model:
one request is hundreds of sequential device steps, and throughput
comes from batching *steps across requests*, not requests.  This engine
is the TPU-first design for that:

- **slot caches, static shapes**: the KV cache is a fixed pool of
  `max_slots` sequence slots, per layer [S, max_seq, H, D].  The decode
  step is ONE jit-compiled program over all S slots, compiled once and
  reused for the life of the server — requests joining or leaving never
  change a shape, so XLA never recompiles (the continuous-batching
  analogue of the engine's batch buckets).
- **paged mode** (`block_size`): the dense pool becomes a shared block
  pool [NB, BS, H, D] + per-slot block tables — HBM scales with
  resident tokens (size it with `cache_blocks`), identical prompt
  prefixes share blocks via a chain-hash index, pool pressure queues
  admissions, and block release is deferred past in-flight waves (the
  zombie-wave hazard).  Shapes stay static: tables ride each dispatch
  as a [S, MB] int32 array (ops/paged_attention.py).
- **prefill/decode split**: prompt ingestion runs as a separate
  bucketed forward (suffix-padded, flash-eligible at long L, one
  compile per bucket) that returns the prompt's k/v for every layer;
  a jitted scatter inserts them into a free slot.  Decode then costs
  O(1) tokens per step.
- **continuous batching, fully asynchronous**: admission enqueues
  prefill + insert + feed-scatter and installs the slot WITHOUT a
  host sync — prompt ingestion rides the same in-flight pipeline as
  decode waves, so an admission burst never stalls live streams by a
  blocking prefill dispatch.  Finished slots free immediately (EOS or
  token budget).  The admission policy is prefill-priority: arrivals
  never wait for the current generation wave to drain (the
  "continuous" in continuous batching).
- **pipelined decode waves**: feed tokens/positions are device-
  resident and chain wave-to-wave through the jit's returned carry;
  the scheduler keeps `pipeline_depth` waves in flight so the D2H
  fetch of wave N overlaps wave N+1's execution — on a high-RTT
  transport the wave period drops from RTT + K steps toward
  max(RTT, K steps).  Stop decisions lag the device by at most
  depth-1 waves (bounded garbage steps, counted in stats).
- **on-device sampling**: greedy, temperature (Gumbel trick), top-k
  and top-p (nucleus) per slot — the mask-then-sample runs on device,
  so only the [S] int32 token vector crosses the host boundary per
  step — never the [S, V] logits (1.6 MB/step for a GPT-2 vocab; the
  host link is the serving bottleneck, ROOFLINE.md).  Noise is keyed
  per request from (seed, absolute position): a seeded request
  reproduces exactly no matter how it was scheduled.  Top-N logprobs
  are computed every step and fetched only when a request asks.
- **donated caches**: the decode step donates the cache buffers, so
  XLA updates them in place — HBM holds ONE cache pool, not
  step-transient copies.

Cache HBM is accounted via `cache_bytes()` so the predictor can admit
params + cache against engine/hbm.py's budget.
"""

import asyncio
import concurrent.futures
import itertools
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import numpy as np

from kfserving_tpu.engine import compile_cache
from kfserving_tpu.observability import attribution
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.profiling import TIMELINE
from kfserving_tpu.protocol.errors import InferenceError, InvalidInput
from kfserving_tpu.reliability import sanitizer

logger = logging.getLogger("kfserving_tpu.engine.generator")

# Monotonic engine ids for the sanitizer's recompile assertion (see
# jax_engine._engine_seq): a model name alone would let a reloaded
# engine inherit its predecessor's warmup declaration.
_generator_seq = itertools.count()


@dataclass
class _Request:
    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float
    top_k: int = 0            # 0 = off
    top_p: float = 1.0        # 1.0 = off
    seed: int = 0             # folded into the sampling noise key
    logprobs: int = 0         # top-N logprobs per token; 0 = off
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    cancelled: bool = False
    # Request latency budget (captured from the ambient contextvar at
    # submit): the scheduler expires the request between decode waves
    # with terminal reason "timeout" — partial text is delivered, the
    # slot frees instead of decoding to the token budget.
    deadline: Optional[Any] = None
    # Per-token logprob records appended by the scheduler in emit
    # order (chosen logprob, [(token_id, logprob)] top-N); consumers
    # read them aligned with the token stream.
    lp_chosen: List[float] = field(default_factory=list)
    lp_top: List[List[Tuple[int, float]]] = field(default_factory=list)
    # Telemetry: the submitting request's trace id (rides onto the
    # TTFT / inter-token-latency / tokens-per-second histograms as
    # OpenMetrics exemplars) and emission timestamps.
    trace_id: Optional[str] = None
    submit_t: float = 0.0
    last_emit_t: Optional[float] = None
    # -- cost attribution (observability/attribution.py): accumulated
    # by the scheduler across the request's whole life (preemptions
    # included), finalized into ONE record at the terminal event.
    # Device ms are the request's EVEN SHARE of each dispatch's busy
    # interval — additive, so per-request costs sum to engine device
    # time instead of multiply-counting shared waves.
    prefill_device_ms: float = 0.0
    decode_device_ms: float = 0.0
    tokens_out: int = 0
    blocks_held: int = 0          # peak slot-table blocks (paged)
    cache_hit_blocks: int = 0     # prompt blocks served by the index
    cache_saved_tokens: int = 0   # hit blocks x block_size
    # Host KV tier (engine/kv_tier.py): prompt blocks faulted back
    # from the host spill tier instead of re-prefilled — kept
    # DISTINCT from the device prefix-cache fields above so the cost
    # record shows which tier earned the savings (the two are
    # additive).  Mutated on the enqueue executor at fault-back
    # drain time; the loop thread awaits the drain before the
    # request can reach any terminal path.
    host_tier_hit_blocks: int = 0
    host_tier_saved_tokens: int = 0
    # Speculative decoding: the draft/verify split of this request's
    # decode device time.  These REFINE decode_device_ms (they are a
    # breakdown of the same busy intervals, not additive terms) — the
    # conservation invariant "prefill + decode sums to engine device
    # time" is untouched.
    spec_draft_ms: float = 0.0
    spec_verify_ms: float = 0.0


@dataclass
class _Active:
    req: _Request
    length: int          # valid cache entries (prompt + generated so far)
    last_token: int      # token to feed at position `length`
    generated: int
    # Content tokens emitted so far — the preemption path re-prefills
    # prompt+tokens to resume a stream exactly (noise is keyed on
    # (seed, absolute position), so the continuation reproduces what
    # an uninterrupted decode would have sampled).
    tokens: List[int] = field(default_factory=list)
    # -- chunked-prefill state (paged mode, cold prompts) --------------
    # prefilling: the slot holds a cold prompt landing in block-aligned
    # chunks between decode waves — it is NOT decodable yet (decode
    # waves park its feed row on an out-of-range sentinel so their
    # speculative writes drop), and _distribute discards its rows.
    prefilling: bool = False
    chunk_next: int = 0        # next chunk index to dispatch
    chunk_total: int = 0
    chunks_inflight: int = 0   # chunk dispatches not yet fetched
    # Per-block insert destinations from the plan (-1 = prefix-cache
    # hit: the shared block already holds the data; a whole chunk of
    # hits skips its dispatch entirely).
    chunk_dest: List[int] = field(default_factory=list)
    # block index -> (chain, block) fresh full-block registrations,
    # DEFERRED until the chunk that writes the block has dispatched —
    # registering at plan time (the monolithic path's provisional
    # trick) would let a sharer's decode read a block whose chunk has
    # not been enqueued yet.
    chunk_regs: Dict[int, Tuple[bytes, int]] = field(
        default_factory=dict)


class GenerationEngine:
    """Continuous-batching token generation over one device/mesh.

    module: a DecoderLM-contract Flax module (models/decoder.py): full
        forward with `return_cache=True` and decode with `kv_cache` +
        `positions`.
    variables: initialized/restored model variables.
    """

    def __init__(self, module, variables, *,
                 max_slots: int = 8,
                 max_seq: int = 512,
                 prefill_buckets: Optional[List[int]] = None,
                 eos_id: Optional[int] = None,
                 steps_per_call: int = 1,
                 pipeline_depth: int = 2,
                 block_size: Optional[int] = None,
                 cache_blocks: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 host_tier_blocks: Optional[int] = None,
                 host_tier_dir: Optional[str] = None,
                 adaptive_depth: bool = True,
                 speculative: Optional[Dict[str, Any]] = None,
                 rng_seed: int = 0,
                 logprob_topk: int = 5,
                 mesh=None,
                 name: str = "decoder"):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.module = module
        self.variables = variables
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        if steps_per_call < 1:
            raise InvalidInput("steps_per_call must be >= 1")
        self.steps_per_call = int(steps_per_call)
        if pipeline_depth < 1:
            raise InvalidInput("pipeline_depth must be >= 1")
        # Decode waves in flight on the device: at depth >= 2 the host
        # fetch of wave N overlaps wave N+1's device execution, so the
        # wave period is max(RTT, K device steps) instead of their sum
        # (jax_engine.py's pipeline_depth, brought to decoding).  The
        # price: EOS/budget/cancel decisions lag the device by up to
        # depth-1 waves — a finishing slot wastes at most
        # (depth-1)*K extra device steps (tracked in stats).
        self.pipeline_depth = int(pipeline_depth)
        # Adaptive depth: stop enqueuing SPECULATIVE waves when every
        # active stream provably finishes (by token budget) within the
        # waves already in flight — those extra waves could only
        # decode garbage (the committed r5 A/B measured ~45% wasted
        # dispatches under uniform traffic at fixed depth 2, and
        # depth_speedup 0.98: depth-2 losing to depth-1).  Staggered
        # traffic keeps remaining work past the horizon, so depth-2's
        # overlap win is untouched there.
        self.adaptive_depth = bool(adaptive_depth)
        cfg = module.config
        if self.max_seq > cfg.max_seq:
            raise InvalidInput(
                f"engine max_seq {self.max_seq} exceeds the model's "
                f"position table {cfg.max_seq}")
        self.eos_id = eos_id
        self.name = name
        self.mesh = mesh
        buckets = sorted(set(prefill_buckets or
                             _pow2_buckets(self.max_seq)))
        if buckets[-1] > self.max_seq:
            raise InvalidInput(
                f"prefill bucket {buckets[-1]} exceeds max_seq "
                f"{self.max_seq}")
        self.prefill_buckets = buckets
        self._rng = jax.random.PRNGKey(rng_seed)
        # Top-N width of the always-computed logprob outputs (fetched
        # from device only when a request asked for them).
        self.logprob_topk = max(1, int(logprob_topk))
        # Default per-request sampling seeds: a deterministic counter —
        # concurrent temperature requests differ from each other, and
        # an explicit seed reproduces exactly.
        self._seed_counter = 0
        # First-dispatch-per-program ledger feeding the KFS_SANITIZE
        # recompile assertion: every (kind, shape-signature) this
        # engine dispatches is noted once through compile_cache —
        # a new program after declared warmup is a violation.  Only
        # touched on the single-threaded enqueue executor.  The
        # source is process-monotonic (never just the model name): a
        # reloaded engine with the same name must not inherit its
        # predecessor's warmup declaration.
        self._dispatched_programs: set = set()
        self.sanitize_source = (
            f"generator:{self.name}:{next(_generator_seq)}")

        n_layers = cfg.num_layers
        cache_dtype = cfg.dtype
        self._cache_dtype = cache_dtype
        # -- paged vs dense cache layout -------------------------------
        # Dense (block_size=None): per-slot [S, max_seq, H, D] — every
        # slot burns max_seq HBM whatever it holds.  Paged: a shared
        # block pool [NB, BS, H, D] + per-slot block tables — HBM
        # scales with resident tokens and identical prompt prefixes
        # share blocks (VERDICT r4 weak #5; the vLLM PagedAttention
        # idea, TPU-shaped: static pool/table shapes, OOB-sentinel
        # scatters, XLA gather attention with a Pallas path to come).
        self.block_size = int(block_size) if block_size else None
        if self.block_size is not None:
            bs = self.block_size
            if self.max_seq % bs != 0:
                raise InvalidInput(
                    f"max_seq {self.max_seq} must be a multiple of "
                    f"block_size {bs}")
            for b in buckets:
                if b % bs != 0:
                    raise InvalidInput(
                        f"prefill bucket {b} must be a multiple of "
                        f"block_size {bs} (paged insert writes whole "
                        f"blocks)")
            self.blocks_per_slot = self.max_seq // bs
            # Parity default: same capacity as the dense pool.  A
            # smaller cache_blocks is the HBM saving — mixed-length
            # traffic rarely needs S full-length slots at once.
            self.num_blocks = int(cache_blocks or
                                  self.max_slots * self.blocks_per_slot)
            pool_shape = (self.num_blocks, bs, cfg.num_heads,
                          cfg.head_dim)
            self._cache_shape = pool_shape
            self._caches = [
                (jnp.zeros(pool_shape, cache_dtype),
                 jnp.zeros(pool_shape, cache_dtype))
                for _ in range(n_layers)
            ]
            # Host-side paging state (guarded by _block_lock: the
            # enqueue thread allocates while cancel() frees on the
            # loop thread).
            import threading
            from collections import OrderedDict

            self._block_lock = threading.Lock()
            self._tables = np.full(
                (self.max_slots, self.blocks_per_slot), -1, np.int32)
            self._free_blocks: deque = deque(range(self.num_blocks))
            self._block_ref = np.zeros(self.num_blocks, np.int64)
            # chain-hash -> block id for FULL prompt blocks (prefix
            # reuse); zero-ref registered blocks linger in
            # _reclaimable (LRU) until allocation pressure evicts.
            self._prefix_index: Dict[bytes, int] = {}
            self._block_chain: Dict[int, bytes] = {}
            self._reclaimable: "OrderedDict[int, None]" = OrderedDict()
            # Hits per LIVE index entry (reuse depth): the /debug/cache
            # census and the hot-chain top-K read this; entries drop
            # with their index entry on eviction/invalidation.
            self._chain_hits: Dict[bytes, int] = {}
            # Eviction accounting by cause (registry twin:
            # kfserving_tpu_generator_block_evictions_total).
            # Capacity evictions split by fate: spilled (the chain
            # survives in the host KV tier) vs dropped (the drop-on-
            # evict baseline — no tier, no chain, or a failed spill).
            self.block_evictions: Dict[str, int] = {
                "capacity_dropped": 0, "capacity_spilled": 0,
                "index_invalidation": 0, "zombie_deferral": 0}
            self.prefill_tokens_saved = 0
            # (release_at_decode_step, [block ids]) — see
            # _free_slot_state for why release is deferred.
            self._deferred_frees: deque = deque()
            # slot -> provisional prefix registrations of its last
            # plan; confirmed once the prefill enqueues, deregistered
            # if the enqueue fails (the blocks were never written).
            self._plan_regs: Dict[int, List[Tuple[bytes, int]]] = {}
            self.prefix_hits = 0
            self.prefix_misses = 0
            # -- host KV tier (engine/kv_tier.py) ----------------------
            # Capacity-evicted prefix blocks spill to a host-RAM mmap
            # tier instead of being dropped; a returning turn's plan
            # probes device index -> host tier -> re-prefill.  Off by
            # default (host_tier_blocks=0); KFS_KV_TIER_BLOCKS is the
            # env twin for server deployments.
            if host_tier_blocks is None:
                try:
                    host_tier_blocks = int(os.environ.get(
                        "KFS_KV_TIER_BLOCKS", "0"))
                except ValueError:
                    host_tier_blocks = 0
            self.kv_tier = None
            if host_tier_blocks and int(host_tier_blocks) > 0:
                from kfserving_tpu.engine.kv_tier import HostKVTier

                block_payload = (2 * n_layers * bs * cfg.num_heads
                                 * cfg.head_dim
                                 * np.dtype(cache_dtype).itemsize)
                self.kv_tier = HostKVTier(
                    block_bytes=block_payload,
                    capacity_blocks=int(host_tier_blocks),
                    directory=(host_tier_dir
                               or os.environ.get("KFS_KV_TIER_DIR")),
                    model=self.name)
            # Spills awaiting their device gather: (chain, block).
            # Appended under _block_lock at eviction time; drained on
            # the enqueue executor BEFORE any dispatch that could
            # rewrite the evicted block (same-thread FIFO is the
            # ordering proof — the gather's snapshot always precedes
            # the overwrite's dispatch).
            self._spill_pending: List[Tuple[bytes, int]] = []
            # Host-tier fault-backs awaiting their pool insert:
            # (chain, block, request, primary).  primary=False rows
            # are coalesced riders on the same chain's single read.
            self._faultback_pending: List[Tuple[bytes, int, Any,
                                                bool]] = []
            # chain -> destination block of a PENDING (undrained)
            # fault-back: a second plan in the same admission batch
            # shares the block instead of reading the tier twice
            # (single-flight).  Guarded by _block_lock.
            self._faultback_by_chain: Dict[bytes, int] = {}
            self.host_tier_tokens_saved = 0
        else:
            self.kv_tier = None  # host tier is paged-mode only
            cache_shape = (self.max_slots, self.max_seq,
                           cfg.num_heads, cfg.head_dim)
            self._cache_shape = cache_shape
            self._caches = [
                (jnp.zeros(cache_shape, cache_dtype),
                 jnp.zeros(cache_shape, cache_dtype))
                for _ in range(n_layers)
            ]
        # -- chunked prefill (paged mode only) -------------------------
        # A cold prompt longer than prefill_chunk_tokens lands in
        # fixed-width chunks that ride the in-flight FIFO between
        # decode waves instead of one monolithic prefill dispatch —
        # live streams see per-chunk stalls, not the whole prompt's
        # device time.  Chunk boundaries align to block_size so the
        # chain-hash prefix index and the block pool are untouched.
        self.prefill_chunk_tokens = (int(prefill_chunk_tokens)
                                     if prefill_chunk_tokens else None)
        if self.prefill_chunk_tokens is not None:
            if self.block_size is None:
                raise InvalidInput(
                    "prefill_chunk_tokens requires the paged cache "
                    "(set block_size): chunk state is carried in the "
                    "block table")
            if self.prefill_chunk_tokens % self.block_size != 0:
                raise InvalidInput(
                    f"prefill_chunk_tokens {self.prefill_chunk_tokens} "
                    f"must be a multiple of block_size "
                    f"{self.block_size} (chunks write whole blocks)")
            if self.prefill_chunk_tokens > self.max_seq:
                raise InvalidInput(
                    f"prefill_chunk_tokens {self.prefill_chunk_tokens} "
                    f"exceeds max_seq {self.max_seq}")
            if self.prefill_chunk_tokens > self.prefill_buckets[-1]:
                # Prompts in (buckets[-1], chunk_tokens] would ride
                # NEITHER path: too long for the monolithic buckets,
                # too short for chunking — and a preempted stream
                # whose merged length lands in that gap could never
                # resume.  Reject the configuration instead of the
                # unlucky prompt.
                raise InvalidInput(
                    f"prefill_chunk_tokens {self.prefill_chunk_tokens} "
                    f"must not exceed the largest prefill bucket "
                    f"{self.prefill_buckets[-1]} (prompts between the "
                    f"two would fit neither the bucketed nor the "
                    f"chunked prefill path)")

        # -- speculative decoding (ROADMAP item 2) ---------------------
        # `speculative` = {"tokens": K >= 1, optional "draft_module",
        # "draft_variables", "draft_window"}.  When None, the
        # KFS_SPECDEC_TOKENS env twin can switch on the n-gram
        # (prompt-lookup) proposer; 0 / unset = off, and the engine is
        # byte-identical to a build without this feature.  With a
        # draft module configured, proposals come from a jitted
        # rolling-window draft scan instead.
        if speculative is None:
            try:
                env_spec = int(os.environ.get("KFS_SPECDEC_TOKENS",
                                              "0"))
            except ValueError:
                env_spec = 0
            if env_spec > 0:
                speculative = {"tokens": env_spec}
        self.spec_tokens = 0
        self._draft_module = None
        self._draft_variables = None
        self._draft_window = 0
        if speculative:
            self.spec_tokens = int(speculative.get("tokens", 0))
            if self.spec_tokens < 0:
                raise InvalidInput(
                    "speculative tokens must be >= 0")
            if self.spec_tokens > 0:
                self._draft_module = speculative.get("draft_module")
                self._draft_variables = speculative.get(
                    "draft_variables")
                if self._draft_module is not None:
                    from kfserving_tpu.engine.speculative import (
                        DEFAULT_DRAFT_WINDOW,
                    )

                    self._draft_window = int(speculative.get(
                        "draft_window", DEFAULT_DRAFT_WINDOW))

        if mesh is not None:
            # Tensor parallelism: the cache shards on the heads axis,
            # exactly like the q/k/v projections that fill it
            # (parallel/sharding.py transformer_rules) — cache writes
            # and decode attention stay device-local per head group;
            # the per-layer psum after the out-projection is the only
            # collective.  Callers pass variables already sharded.
            from jax.sharding import NamedSharding, PartitionSpec

            tp = mesh.shape.get("tp", 1)
            heads_axis = "tp" if cfg.num_heads % max(tp, 1) == 0 else None
            sharding = NamedSharding(
                mesh, PartitionSpec(None, None, heads_axis, None))
            self._caches = [
                (jax.device_put(k, sharding), jax.device_put(v, sharding))
                for k, v in self._caches
            ]

        base_key = self._rng
        lp_n = self.logprob_topk

        def mask_to_support(logits, top_ks, top_ps):
            """Restrict logits to the top-k / nucleus support.  Both
            knobs are per-row; 0 / 1.0 disable them.  One sort serves
            both masks."""
            v = logits.shape[-1]
            sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
            k_eff = jnp.where((top_ks <= 0) | (top_ks >= v), v,
                              top_ks)
            kth = jnp.take_along_axis(sorted_desc,
                                      (k_eff - 1)[:, None], axis=-1)
            keep = logits >= kth
            # Nucleus: keep the smallest prefix of the sorted
            # distribution whose mass reaches top_p (the first token
            # is always kept — cumsum-before-it is 0).
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (cum - probs) < top_ps[:, None]
            n_keep = jnp.maximum(jnp.sum(keep_sorted, axis=-1), 1)
            p_thresh = jnp.take_along_axis(
                sorted_desc, (n_keep - 1)[:, None], axis=-1)
            keep &= logits >= p_thresh
            return jnp.where(keep, logits,
                             jnp.finfo(logits.dtype).min)

        def sample(logits, temps, top_ks, top_ps, seeds, noise_pos):
            """logits [B, V] float32.  Noise is keyed per ROW from
            (request seed, absolute position), never from wave or slot
            identity — a request's sampled tokens reproduce exactly
            for a given seed no matter how it was scheduled."""
            greedy = jnp.argmax(logits, axis=-1)
            need_mask = jnp.any((top_ks > 0) | (top_ps < 1.0))
            masked = jax.lax.cond(
                need_mask,
                lambda l: mask_to_support(l, top_ks, top_ps),
                lambda l: l, logits)

            def row_key(seed, pos):
                return jax.random.fold_in(
                    jax.random.fold_in(base_key, seed), pos)

            keys = jax.vmap(row_key)(seeds, noise_pos)
            gumbel = jax.vmap(
                lambda k: jax.random.gumbel(k, (logits.shape[-1],))
            )(keys)
            scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
            sampled = jnp.argmax(scaled + gumbel, axis=-1)
            return jnp.where(temps <= 0.0, greedy,
                             sampled).astype(jnp.int32)

        def logprob_of(logits, chosen):
            """Chosen-token logprob + top-N (ids, logprobs) over the
            UNMASKED distribution — diagnostics follow the model, not
            the sampler's support restriction."""
            lps = jax.nn.log_softmax(logits, axis=-1)
            chosen_lp = jnp.take_along_axis(
                lps, chosen[:, None].astype(jnp.int32), axis=-1)[:, 0]
            top_lps, top_ids = jax.lax.top_k(lps, lp_n)
            return chosen_lp, top_ids.astype(jnp.int32), top_lps

        k_steps = self.steps_per_call
        paged = self.block_size is not None

        def decode_fn(variables, caches, table, tokens, positions,
                      temps, top_ks, top_ps, seeds):
            """K decode steps in ONE device dispatch (lax.scan): on a
            high-RTT link each host round trip costs ~an RTT, so
            single-token stepping caps tokens/s at 1/RTT per wave;
            scanning K steps on device multiplies that by K.  Tokens
            feed forward on device; the host sees [S, K] at once (stop
            conditions checked per chunk — at most K-1 wasted steps
            after an EOS/budget stop).  Also returns the final carry's
            feed tokens/positions as device arrays: the pipelined
            scheduler chains dispatch N+1 off them without a host
            round trip."""
            def step(carry, _):
                caches, tokens, positions = carry
                kv = ([(k, v, table) for k, v in caches] if paged
                      else caches)
                logits, new_caches = module.apply(
                    variables, tokens[:, None], positions=positions,
                    kv_cache=kv)
                lg = logits[:, 0]
                # The token being sampled extends a prefix of length
                # positions+1 — the noise index is that length, so
                # prefill (length L) and decode agree on the sequence
                # L, L+1, ... per request.
                nxt = sample(lg, temps, top_ks, top_ps, seeds,
                             positions + 1)
                lp = logprob_of(lg, nxt)
                return (new_caches, nxt, positions + 1), (nxt, lp)

            (caches, next_tokens, next_positions), (toks, lps) = \
                jax.lax.scan(step, (caches, tokens, positions),
                             None, length=k_steps)
            chosen_lp, top_ids, top_lps = lps
            # scan stacks on axis 0: [K, S, ...] -> [S, K, ...]
            return (toks.T, caches, next_tokens, next_positions,
                    chosen_lp.T, jnp.swapaxes(top_ids, 0, 1),
                    jnp.swapaxes(top_lps, 0, 1))

        # Donate caches AND the feed arrays: in-place HBM update, one
        # resident pool; the feed tokens/positions chain wave-to-wave
        # entirely on device.  The block table (arg 2) is NOT donated:
        # the host re-sends it per wave (2 KB; it changes at
        # allocation time).
        self._decode = jax.jit(decode_fn, donate_argnums=(1, 3, 4))

        def feed_update_fn(tokens, positions, slot_arr, new_tokens,
                           new_positions):
            """Scatter newly admitted requests' first feed token and
            position into the device-resident feed arrays (OOB
            sentinel rows drop, like the cache insert)."""
            return (tokens.at[slot_arr].set(new_tokens, mode="drop"),
                    positions.at[slot_arr].set(new_positions,
                                               mode="drop"))

        self._feed_update = jax.jit(feed_update_fn,
                                    donate_argnums=(0, 1))
        # Device-resident feed state: the token each slot feeds next
        # and its position.  Rows of freed slots go stale — that is
        # deliberate; a garbage decode on a free slot is harmless
        # (its tokens are dropped at distribute, OOB cache writes
        # drop, gathers clamp) and admission overwrites the row.
        self._feed_tokens = jnp.zeros(self.max_slots, jnp.int32)
        self._feed_positions = jnp.zeros(self.max_slots, jnp.int32)

        def prefill_fn(variables, ids, lengths, temps, top_ks, top_ps,
                       seeds):
            # logit_positions: the LM head runs only on each row's
            # last real token — sampling never needs the [B, L, V]
            # logits cube, and at a 4096 bucket the full-cube head
            # matmul dominated prefill FLOPs.  Numerically identical
            # per row to slicing the full cube (norm + head are
            # per-position), so the chunked path (which uses the same
            # sliced head) samples the same first token.
            logits, caches = module.apply(variables, ids,
                                          kv_lengths=lengths,
                                          return_cache=True,
                                          logit_positions=lengths - 1)
            last = logits[:, 0]
            first_tokens = sample(last, temps, top_ks, top_ps, seeds,
                                  lengths)
            chosen_lp, top_ids, top_lps = logprob_of(last,
                                                     first_tokens)
            return first_tokens, caches, chosen_lp, top_ids, top_lps

        # One executable per prompt bucket (jit caches by shape).
        self._prefill = jax.jit(prefill_fn)

        if paged:
            def chunk_prefill_fn(variables, caches, table, ids, qpos,
                                 last_idx, temps, top_ks, top_ps,
                                 seeds, noise_pos):
                """One chunk of a cold prompt: ids [1, C] write their
                k/v through the slot's block table at absolute
                positions qpos [1, C] (padding rows of a partial final
                chunk park on an out-of-range sentinel and drop), and
                attend per-query-causally over the pool — earlier
                chunks are already resident, so cross-chunk attention
                reads them exactly like decode does.  The head runs
                only at last_idx; the sampled token matters only for
                the FINAL chunk (it becomes the stream's first token,
                noise-keyed on the full prompt length for parity with
                monolithic prefill) — earlier chunks discard it."""
                kv = [(k, v, table) for k, v in caches]
                logits, new_caches = module.apply(
                    variables, ids, positions=qpos, kv_cache=kv,
                    logit_positions=last_idx)
                lg = logits[:, 0]
                first = sample(lg, temps, top_ks, top_ps, seeds,
                               noise_pos)
                chosen_lp, top_ids, top_lps = logprob_of(lg, first)
                return first, new_caches, chosen_lp, top_ids, top_lps

            self._chunk_prefill = jax.jit(chunk_prefill_fn,
                                          donate_argnums=(1,))

        self._spec_draft_fn = None
        if self.spec_tokens > 0:
            spec_kp1 = self.spec_tokens + 1

            def spec_verify_fn(variables, caches, table, last_tokens,
                               draft_toks, positions, temps, top_ks,
                               top_ps, seeds):
                """Verify K draft tokens per slot in ONE Lq=K+1
                dispatch.  Row i feeds [last_token, draft_0..K-1] at
                absolute positions [L, L+K] (parked rows ride the
                max_seq sentinel: their writes drop / clamp and their
                samples are discarded).  logit_positions asks the LM
                head for ALL K+1 positions — position j's logits see
                exactly the prefix a sequential decode would have at
                step j, so sampling them with the SAME per-row
                (seed, position) noise keys reproduces sequential
                decode's draws bit-exactly.  Exact-match acceptance of
                the longest agreeing prefix is then rejection sampling
                under the slot's deterministic noise key: the target's
                draw at a position is a point, and accept-iff-equal is
                the degenerate (and parity-exact) rejection rule.
                Rollback past the first rejection needs NO cache
                surgery — the host length pointer simply does not
                advance over rejected positions, and the garbage k/v
                written there is overwritten by later waves before any
                query can attend it (writes precede attention in every
                dispatch, and positions advance monotonically)."""
                tokens = jnp.concatenate(
                    [last_tokens[:, None], draft_toks], axis=1)
                kv = ([(k, v, table) for k, v in caches] if paged
                      else caches)
                s_rows = tokens.shape[0]
                gather = jnp.broadcast_to(
                    jnp.arange(spec_kp1, dtype=jnp.int32)[None, :],
                    (s_rows, spec_kp1))
                logits, new_caches = module.apply(
                    variables, tokens, positions=positions,
                    kv_cache=kv, logit_positions=gather)
                flat = logits.reshape(s_rows * spec_kp1, -1)

                def rep(a):
                    return jnp.repeat(a, spec_kp1)

                # noise index = length of the prefix each draw
                # extends: position p's sample starts a prefix of
                # p + 1 tokens — identical keying to decode_fn.
                samples = sample(flat, rep(temps), rep(top_ks),
                                 rep(top_ps), rep(seeds),
                                 (positions + 1).reshape(-1))
                chosen_lp, top_ids, top_lps = logprob_of(flat,
                                                         samples)
                # draft_toks are echoed through so the host reads
                # proposals + verdicts in the same fetch: the draft
                # arm costs ONE host round trip per spec wave, same
                # as a plain decode wave.
                return (samples.reshape(s_rows, spec_kp1), draft_toks,
                        new_caches,
                        chosen_lp.reshape(s_rows, spec_kp1),
                        top_ids.reshape(s_rows, spec_kp1, lp_n),
                        top_lps.reshape(s_rows, spec_kp1, lp_n))

            self._spec_verify = jax.jit(spec_verify_fn,
                                        donate_argnums=(1,))
            from kfserving_tpu.engine.speculative import NGramProposer

            self._ngram = NGramProposer(self.spec_tokens)
            if self._draft_module is not None:
                from kfserving_tpu.engine.speculative import (
                    make_draft_proposer,
                )

                self._spec_draft_fn = make_draft_proposer(
                    jax, self._draft_module, self.max_slots,
                    self._draft_window, self.spec_tokens)

        if paged:
            from kfserving_tpu.ops.paged_attention import paged_insert

            def insert_fn(caches, new_caches, dest_blocks):
                """Scatter a prefill batch's k/v into pool blocks.
                dest_blocks [B, chunks] int32; -1 chunks drop (bucket
                padding rows, and prefix-cache hits whose shared
                blocks already hold the data)."""
                out = []
                for (pk, pv), (k_new, v_new) in zip(caches,
                                                    new_caches):
                    pk, pv = paged_insert(pk, pv, k_new, v_new,
                                          dest_blocks, None)
                    out.append((pk, pv))
                return out
        else:
            def insert_fn(caches, new_caches, slots):
                """Scatter a prefill batch's k/v into its slots.
                slots is [B] int32; padding rows carry the
                out-of-bounds sentinel max_slots and mode='drop'
                discards them (a prefill batch is padded to a pow2 B
                bucket to bound compile count)."""
                out = []
                for (k_cache, v_cache), (k_new, v_new) in zip(
                        caches, new_caches):
                    lb = k_new.shape[1]
                    out.append((
                        k_cache.at[slots, :lb].set(
                            k_new.astype(k_cache.dtype), mode="drop"),
                        v_cache.at[slots, :lb].set(
                            v_new.astype(v_cache.dtype), mode="drop"),
                    ))
                return out

        self._insert = jax.jit(insert_fn, donate_argnums=(0,))

        if paged and self.kv_tier is not None:
            def gather_blocks_fn(caches, idx):
                """Snapshot the k/v of pool blocks `idx` [N] as
                standalone device arrays (NOT donating the caches):
                the spill path fetches the snapshot on the fetch
                executor while later dispatches keep mutating the
                pool — the data dependency pins the pre-overwrite
                contents."""
                return [(k[idx], v[idx]) for k, v in caches]

            self._gather_blocks = jax.jit(gather_blocks_fn)

        # Two executors with distinct roles: `_executor` owns blocking
        # D2H fetches (each ~an RTT) — TWO workers, because fetches
        # are submitted EAGERLY at enqueue time and a decode wave's
        # tokens must not queue behind a prefill fetch's round trip
        # (results are awaited in FIFO order regardless of completion
        # order).  `_enqueue_executor` owns dispatch enqueues (fast
        # post-compile, but the FIRST call per shape traces + compiles
        # for seconds — that must not freeze the asyncio loop, and
        # must not queue behind an in-flight fetch either, or
        # admission would stall on decode).  Device-side ordering
        # comes from the data-dependency chain on the cache/feed
        # handles, not from host thread order.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=2,
            thread_name_prefix=f"generator-{name}")
        self._enqueue_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"generator-enq-{name}")
        self._slots: List[Optional[_Active]] = [None] * self.max_slots
        self._pending: deque = deque()
        # Growth starvation: a decodable slot's table cannot cover the
        # horizon and a mid-prefill slot just yielded its blocks — the
        # scheduler HOLDS (no new admissions, no new waves) until the
        # yielded blocks mature through the zombie-deferral window,
        # instead of preempting a stream that already holds context.
        self._growth_starved = False
        self._wakeup: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False

        # stats
        self.tokens_generated = 0
        self.decode_steps = 0       # device dispatches
        self._token_steps = 0       # dispatches x steps_per_call
        self.prefills = 0           # prefill dispatches
        self.prefill_requests = 0   # requests admitted through them
        self.requests_finished = 0
        self.preemptions = 0        # paged: growth-pressure requeues
        self.prefill_chunks = 0     # chunked-prefill dispatches
        self.prefill_chunks_skipped = 0  # whole-chunk prefix hits
        self.chunked_admissions = 0
        # Adaptive-depth accounting: waves the governor refused to
        # enqueue (they could only decode garbage) and the depth the
        # pipeline last ran at.
        self.suppressed_waves = 0
        self._depth_effective = self.pipeline_depth
        # Speculative-decoding accounting (engine twins of the
        # kfserving_tpu_specdec_* registry families).
        self.spec_waves = 0
        self.spec_proposed_tokens = 0   # K per live row per wave
        self.spec_accepted_tokens = 0   # draft tokens that matched
        self.spec_emitted_tokens = 0    # accepted + the bonus draws
        self.spec_fallbacks: Dict[str, int] = {}
        # Bounded accepted-length reservoir for the stats()/cache
        # p50/p99 (full-fidelity histogram lives in the registry).
        self._spec_lengths: deque = deque(maxlen=4096)
        self._spec_draft_s = 0.0
        self._spec_verify_s = 0.0
        self._occupied_slot_steps = 0
        self._wasted_token_steps = 0  # garbage steps past a finish
        # Union of enqueue->fetch intervals (overlap-corrected at
        # depth >= 2, so the stat stays <= wall clock).
        self._decode_device_s = 0.0
        self._last_fetch_done = 0.0
        self._decode_wait_s = 0.0     # host blocked in decode fetches
        self._prefill_wait_s = 0.0    # host blocked in prefill fetches
        self._prefill_device_s = 0.0
        # -- roofline accounting (promoted to registry gauges by
        # observability/profiling/roofline.py at /metrics scrape) ------
        # Analytic FLOP model: 2*P matmul FLOPs per token plus
        # attention's 4*layers*heads*head_dim per resident context
        # position (QK^T and AV, 2 FLOPs per MAC each).  Counted over
        # LIVE slots only — garbage waves burn device time without
        # adding useful FLOPs, so decode_mfu is a goodput-weighted
        # floor on chip utilization, matching ROOFLINE.md's framing.
        self._n_params = int(sum(
            int(np.prod(x.shape))
            for x in self._jax.tree.leaves(variables)))
        self._param_read_bytes = self.param_bytes()
        self._flops_matmul_per_token = 2.0 * self._n_params
        self._attn_flops_coeff = (4.0 * n_layers * cfg.num_heads
                                  * cfg.head_dim)
        self._kv_bytes_per_token = (2 * n_layers * cfg.num_heads
                                    * cfg.head_dim
                                    * np.dtype(cache_dtype).itemsize)
        from kfserving_tpu.engine.jax_engine import device_peak_flops
        from kfserving_tpu.observability.profiling.roofline import (
            device_peak_hbm_bw,
        )

        self._peak_flops = device_peak_flops()
        self._peak_hbm_bw = device_peak_hbm_bw()
        self._decode_flops = 0.0
        self._prefill_flops = 0.0
        self._decode_hbm_bytes = 0.0  # params + resident KV reads
        # Per-prefill-bucket token padding: {bucket: [real, padded]}
        # (updated on the enqueue thread, read by stats(); plain dict
        # ops under the GIL).
        self._prefill_bucket_tokens: Dict[int, List[float]] = {}
        # Growth-HOLD window tracking for the event timeline.
        self._hold_since: Optional[float] = None

    # -- public API --------------------------------------------------------
    def cache_bytes(self) -> int:
        per_buf = int(np.prod(self._cache_shape)) * \
            np.dtype(self._cache_dtype).itemsize
        return per_buf * 2 * len(self._caches)

    def param_bytes(self) -> int:
        jax = self._jax
        return sum(int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
                   for x in jax.tree.leaves(self.variables))

    async def generate(self, prompt_ids, max_new_tokens: int = 32,
                       temperature: float = 0.0, **sampling
                       ) -> AsyncIterator[Tuple[int, Optional[str]]]:
        """Yields (token_id, finish_reason) events.  Intermediate
        tokens arrive as (id, None); the stream ends with either
        (id, 'length') — the budget-final token — or (None, 'eos'),
        since EOS is a stop signal, not content.  Engine failures
        surface as InferenceError mid-stream."""
        req = self.submit(prompt_ids, max_new_tokens, temperature,
                          **sampling)
        async for event in self.stream(req):
            yield event

    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, *, top_k: int = 0,
               top_p: float = 1.0, seed: Optional[int] = None,
               logprobs: int = 0) -> _Request:
        """Validate and enqueue a request NOW (InvalidInput surfaces to
        the caller before any response bytes are committed — the
        streaming route depends on this).  Pair with `stream()`."""
        return self._submit(prompt_ids, max_new_tokens, temperature,
                            top_k=top_k, top_p=top_p, seed=seed,
                            logprobs=logprobs)

    async def stream(self, req: _Request
                     ) -> AsyncIterator[Tuple[Optional[int],
                                              Optional[str]]]:
        while True:
            token, reason = await req.out.get()
            if reason is not None and reason.startswith("error"):
                raise InferenceError(reason)
            yield token, reason
            if reason is not None:
                return

    def cancel(self, req: _Request) -> None:
        """Abandon a request: a consumer that stops caring (client
        disconnect, stop-sequence match) must free the decode slot —
        otherwise the engine decodes to the full token budget for
        nobody.  Runs on the event loop thread (the same thread as all
        slot bookkeeping).  Idempotent; a finished request is a no-op.
        The slot stops being fed at the next wave boundary."""
        if req.cancelled:
            return
        req.cancelled = True
        try:
            self._pending.remove(req)
            req.out.put_nowait((None, "cancelled"))
            self._finalize_cost(req, "cancelled")
            self.requests_finished += 1
            return
        except ValueError:
            pass
        for i, s in enumerate(self._slots):
            if s is not None and s.req is req:
                self._free_slot_state(i)
                self.requests_finished += 1
                req.out.put_nowait((None, "cancelled"))
                self._finalize_cost(req, "cancelled")
                return
        # Neither pending nor active: either already finished (no-op)
        # or mid-prefill on the executor — the install step checks
        # `cancelled` and drops it.

    async def complete(self, prompt_ids, max_new_tokens: int = 32,
                       temperature: float = 0.0, **sampling
                       ) -> Tuple[List[int], str]:
        tokens: List[int] = []
        reason = "length"
        async for token, fin in self.generate(prompt_ids,
                                              max_new_tokens,
                                              temperature,
                                              **sampling):
            if token is not None:
                tokens.append(token)
            if fin is not None:
                reason = fin
        return tokens, reason

    def _submit(self, prompt_ids, max_new_tokens, temperature, *,
                top_k: int = 0, top_p: float = 1.0,
                seed: Optional[int] = None,
                logprobs: int = 0) -> _Request:
        if self._closed:
            raise InvalidInput(f"generator {self.name} is closed")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise InvalidInput("empty prompt")
        chunked = (self.prefill_chunk_tokens is not None
                   and ids.size > self.prefill_chunk_tokens)
        if ids.size > self.prefill_buckets[-1] and not chunked:
            # Chunked (cold) prompts never ride a prefill bucket —
            # their ceiling is max_seq via the budget clamp below.
            raise InvalidInput(
                f"prompt length {ids.size} exceeds the largest prefill "
                f"bucket {self.prefill_buckets[-1]}")
        if self.block_size is not None:
            need = -(-int(ids.size) // self.block_size)
            if need > self.num_blocks:
                raise InvalidInput(
                    f"prompt needs {need} cache blocks but the pool "
                    f"holds {self.num_blocks}")
        if max_new_tokens < 1:
            raise InvalidInput("max_new_tokens must be >= 1")
        if not 0.0 < float(top_p) <= 1.0:
            raise InvalidInput("top_p must be in (0, 1]")
        if top_k < 0:
            raise InvalidInput("top_k must be >= 0")
        if logprobs < 0 or logprobs > self.logprob_topk:
            raise InvalidInput(
                f"logprobs must be in [0, {self.logprob_topk}]")
        # Clamp the budget to cache capacity: prompt + generated tokens
        # must fit max_seq.
        budget = min(int(max_new_tokens), self.max_seq - int(ids.size))
        if budget < 1:
            raise InvalidInput(
                f"prompt length {ids.size} leaves no room to generate "
                f"within max_seq {self.max_seq}")
        if seed is None:
            seed = self._seed_counter
            self._seed_counter += 1
        from kfserving_tpu.reliability.deadline import current_deadline
        from kfserving_tpu.tracing import current_request_id

        req = _Request(ids, budget, float(temperature),
                       top_k=int(top_k), top_p=float(top_p),
                       seed=int(seed) & 0x7FFFFFFF,
                       logprobs=int(logprobs),
                       deadline=current_deadline(),
                       trace_id=current_request_id.get(),
                       submit_t=time.perf_counter())
        self._pending.append(req)
        self._ensure_loop()
        return req

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._wakeup = asyncio.Event()
            self._loop_task = asyncio.get_running_loop().create_task(
                self._run())
        self._wakeup.set()

    async def close(self):
        self._closed = True
        if self._loop_task is not None:
            if self._wakeup is not None:
                self._wakeup.set()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
        self._executor.shutdown(wait=True)
        self._enqueue_executor.shutdown(wait=True)
        if self.kv_tier is not None:
            self.kv_tier.close()

    def shutdown_nowait(self):
        """Synchronous best-effort teardown (repository unload runs
        outside async context): stop admitting, let the scheduler task
        drain, release the worker threads without joining."""
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        self._executor.shutdown(wait=False)
        self._enqueue_executor.shutdown(wait=False)
        if self.kv_tier is not None:
            self.kv_tier.close()

    def load_gauges(self) -> Dict[str, int]:
        """Instantaneous saturation signal for the autoscaler: a
        generative replica saturates by slot occupancy and pending
        prefill depth, NOT by request count (8 slow streams = '8
        inflight' at the router = invisible saturation)."""
        return {
            "active_slots": sum(1 for s in self._slots
                                if s is not None),
            "pending": len(self._pending),
            "max_slots": self.max_slots,
        }

    def stats(self) -> Dict[str, Any]:
        steps = max(1, self._token_steps)
        out = {
            "tokens_generated": self.tokens_generated,
            "decode_steps": self.decode_steps,
            "token_steps": self._token_steps,
            "steps_per_call": self.steps_per_call,
            "prefills": self.prefills,
            "prefill_requests": self.prefill_requests,
            "requests_finished": self.requests_finished,
            "slot_occupancy": round(
                self._occupied_slot_steps / (steps * self.max_slots), 4),
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "pipeline_depth": self.pipeline_depth,
            "adaptive_depth": self.adaptive_depth,
            "depth_effective": self._depth_effective,
            "suppressed_waves": self.suppressed_waves,
            "wasted_token_steps": self._wasted_token_steps,
            "cache_bytes": self.cache_bytes(),
            "decode_device_s": round(self._decode_device_s, 4),
            "decode_wait_s": round(self._decode_wait_s, 4),
            "prefill_wait_s": round(self._prefill_wait_s, 4),
            "prefill_device_s": round(self._prefill_device_s, 4),
        }
        # -- roofline block (promoted to registry gauges by
        # observability/profiling/roofline.py; keys must stay in sync
        # with its consumed-key tables) --------------------------------
        if self._decode_flops > 0 and self._decode_device_s > 0:
            achieved = self._decode_flops / self._decode_device_s
            out["achieved_decode_tflops"] = round(achieved / 1e12, 6)
            if self._peak_flops:
                out["decode_mfu"] = round(
                    achieved / self._peak_flops, 6)
        if self._prefill_flops > 0 and self._prefill_device_s > 0:
            achieved = self._prefill_flops / self._prefill_device_s
            out["achieved_prefill_tflops"] = round(achieved / 1e12, 6)
            if self._peak_flops:
                out["prefill_mfu"] = round(
                    achieved / self._peak_flops, 6)
        if self.tokens_generated + self._wasted_token_steps > 0:
            out["goodput_ratio"] = round(
                self.tokens_generated
                / (self.tokens_generated + self._wasted_token_steps),
                4)
        if self._decode_hbm_bytes > 0 and self._decode_device_s > 0:
            rate = self._decode_hbm_bytes / self._decode_device_s
            out["decode_hbm_gb_s"] = round(rate / 1e9, 3)
            if self._peak_hbm_bw:
                out["hbm_bw_util"] = round(
                    min(1.0, rate / self._peak_hbm_bw), 6)
        if self._prefill_bucket_tokens:
            # .copy() is atomic under the GIL; iterating the live dict
            # could race an enqueue-thread insert of a new bucket.
            out["prefill_bucket_pad_waste"] = {
                f"s{b}": round(1.0 - real / padded, 4)
                for b, (real, padded)
                in sorted(self._prefill_bucket_tokens.copy().items())
                if padded > 0}
        if self.block_size is not None:
            with self._block_lock:
                refd = int(np.sum(self._block_ref > 0))
                resident = sum(s.length for s in self._slots
                               if s is not None)
                # Fragmentation over per-slot TABLE blocks, not refd:
                # a shared prefix block appears in every sharer's
                # table AND every sharer's length, so numerator and
                # denominator count it the same number of times —
                # against refd (which counts it once) the ratio went
                # negative exactly in the shared-prompt regime.
                table_blocks = int(np.sum(self._tables >= 0))
                frag = (1.0 - resident
                        / (table_blocks * self.block_size)
                        if table_blocks else 0.0)
                out["paged"] = {
                    "block_size": self.block_size,
                    "pool_blocks": self.num_blocks,
                    # Canonical names, matching the timeline pool
                    # counter samples (_record_pool_sample).  The
                    # deprecated blocks_free/blocks_reclaimable aliases
                    # (ISSUE 13's one-release grace) are gone.
                    "free_blocks": len(self._free_blocks),
                    "reclaimable_blocks": len(self._reclaimable),
                    "prefix_hits": self.prefix_hits,
                    "prefix_misses": self.prefix_misses,
                    "prefill_tokens_saved": self.prefill_tokens_saved,
                    "index_entries": len(self._prefix_index),
                    "pool_occupancy_ratio": round(
                        min(1.0, refd / max(1, self.num_blocks)), 4),
                    "fragmentation_ratio": round(
                        min(1.0, max(0.0, frag)), 4),
                    "evictions": dict(self.block_evictions),
                    "preemptions": self.preemptions,
                }
            if self.kv_tier is not None:
                out["paged"]["host_tier_tokens_saved"] = \
                    self.host_tier_tokens_saved
                out["host_tier"] = self.kv_tier.debug()
            if self.prefill_chunk_tokens is not None:
                out["chunked_prefill"] = {
                    "chunk_tokens": self.prefill_chunk_tokens,
                    "admissions": self.chunked_admissions,
                    "chunks_dispatched": self.prefill_chunks,
                    "chunks_skipped_shared": self.prefill_chunks_skipped,
                }
        if self.spec_tokens:
            out["speculative"] = self.spec_debug()
        return out

    def spec_debug(self) -> Dict[str, Any]:
        """Speculative-decoding snapshot for stats() and the
        /debug/cache body (the router federates per-replica acceptance
        rates from here, like the prefix census)."""
        lengths = sorted(self._spec_lengths)

        def lpct(q: float) -> int:
            if not lengths:
                return 0
            return lengths[min(len(lengths) - 1,
                               int(len(lengths) * q))]

        proposed = self.spec_proposed_tokens
        return {
            "tokens": self.spec_tokens,
            "proposer": ("draft" if self._spec_draft_fn is not None
                         else "ngram"),
            "waves": self.spec_waves,
            "proposed_tokens": proposed,
            "accepted_tokens": self.spec_accepted_tokens,
            "emitted_tokens": self.spec_emitted_tokens,
            "acceptance_rate": (round(
                self.spec_accepted_tokens / proposed, 4)
                if proposed else 0.0),
            "accepted_length_p50": lpct(0.50),
            "accepted_length_p99": lpct(0.99),
            "draft_device_s": round(self._spec_draft_s, 4),
            "verify_device_s": round(self._spec_verify_s, 4),
            "draft_param_bytes": self.draft_param_bytes(),
            "fallbacks": dict(self.spec_fallbacks),
        }

    def cache_debug(self, top_k: int = 10) -> Dict[str, Any]:
        """The per-replica `GET /debug/cache` body: prefix-index
        census (entry count, reuse-depth distribution, top-K hot
        chains by hit count) plus the pool occupancy snapshot — the
        exact feed prefix-affinity routing (ROADMAP item 3) and the
        LRU HBM residency manager (item 4) will read, federated by
        the router under the `replica` label."""
        if self.block_size is None:
            out = {"paged": False}
            if self.spec_tokens:
                out["speculative"] = self.spec_debug()
            return out
        with self._block_lock:
            census = {chain: self._chain_hits.get(chain, 0)
                      for chain in self._prefix_index}
        depths = sorted(census.values())

        def pct(q: float) -> int:
            if not depths:
                return 0
            return depths[min(len(depths) - 1, int(len(depths) * q))]

        hot = sorted(census.items(), key=lambda kv: (-kv[1], kv[0]))
        hot = hot[:max(0, int(top_k))]
        ret = {
            "paged": True,
            "index_entries": len(census),
            "reuse_depth": {
                "p50": pct(0.50),
                "p99": pct(0.99),
                "max": depths[-1] if depths else 0,
                "mean": (round(sum(depths) / len(depths), 3)
                         if depths else 0.0),
            },
            "hot_chains": [{"chain": chain.hex(), "hits": hits}
                           for chain, hits in hot],
            # stats() re-takes the block lock — called OUTSIDE the
            # census hold above.
            "pool": self.stats()["paged"],
        }
        if self.spec_tokens:
            ret["speculative"] = self.spec_debug()
        return ret

    # -- paged-cache bookkeeping -------------------------------------------
    # All mutation happens under _block_lock: the enqueue thread
    # allocates during prefill planning is NOT true — planning runs on
    # the loop thread, but cancel() (loop) can race wave enqueues
    # (enqueue thread) that read tables, and deferred frees run on the
    # loop thread; the lock keeps the free-list/refcount state sane.

    def _alloc_block_locked(self) -> Optional[int]:
        if self._free_blocks:
            return self._free_blocks.popleft()
        if self._reclaimable:
            # Evict the LRU zero-ref registered block: prefix entries
            # linger for reuse only until allocation pressure.
            blk, _ = self._reclaimable.popitem(last=False)
            chain = self._block_chain.pop(blk, None)
            if chain is not None and self._prefix_index.get(chain) == blk:
                # Only drop the index entry this block actually backs —
                # a concurrent duplicate admission may have re-pointed
                # the chain at a different (still-resident) block.
                self._prefix_index.pop(chain, None)
                self._chain_hits.pop(chain, None)
            # Fate of the evicted state: spill to the host tier when
            # one is wired (the chain digest is the key; the device
            # gather rides the enqueue executor BEFORE any dispatch
            # can rewrite blk), otherwise — or for an unregistered
            # block — it drops, the baseline.  Spill outcomes resolve
            # asynchronously: the cause counter lands when the tier
            # write commits (capacity_spilled) or fails
            # (capacity_dropped), keeping the split honest under
            # chaos injection.
            if self.kv_tier is None or chain is None:
                self._count_capacity_locked("capacity_dropped", blk)
            elif self.kv_tier.contains(chain):
                # Already host-resident (spilled on a previous
                # eviction and faulted back since): the state is
                # safe, no second copy needed.
                self._count_capacity_locked("capacity_spilled", blk)
            else:
                self._spill_pending.append((chain, blk))
            return blk
        return None

    def _count_capacity_locked(self, cause: str, blk: int) -> None:
        self.block_evictions[cause] += 1
        obs.generator_block_evictions_total().labels(
            model=self.name, cause=cause).inc()
        TIMELINE.record("host", "cache.evict",
                        attrs={"cause": cause, "block": blk})

    def _ref_block_locked(self, blk: int) -> None:
        self._block_ref[blk] += 1
        self._reclaimable.pop(blk, None)

    def _unref_block_locked(self, blk: int) -> None:
        self._block_ref[blk] -= 1
        if self._block_ref[blk] <= 0:
            self._block_ref[blk] = 0
            if blk in self._block_chain:
                self._reclaimable[blk] = None  # linger for reuse
            else:
                self._free_blocks.append(blk)

    def _free_slot_state(self, i: int) -> None:
        """Free slot i AND schedule its blocks' release."""
        self._slots[i] = None
        self._schedule_block_release(i)

    def _deregister_plan(self, slot: int) -> None:
        """Remove a slot's PROVISIONAL prefix registrations (its
        prefill never enqueued, so the registered blocks hold no
        data).  No-op once the plan was confirmed."""
        if self.block_size is None:
            return
        with self._block_lock:
            dropped = 0
            for chain, blk in self._plan_regs.pop(slot, []):
                if self._prefix_index.pop(chain, None) is not None:
                    dropped += 1
                    self._chain_hits.pop(chain, None)
                self._block_chain.pop(blk, None)
            self._count_invalidations_locked(dropped)

    def _count_invalidations_locked(self, dropped: int) -> None:
        """Account `dropped` prefix-index entries removed because
        their planned writes never dispatched (plan rollback / enqueue
        failure) — a stale chain surviving here is the share-unwritten-
        blocks bug class, so the count is the telemetry proof the
        invalidation path ran."""
        if dropped <= 0:
            return
        self.block_evictions["index_invalidation"] += dropped
        obs.generator_block_evictions_total().labels(
            model=self.name, cause="index_invalidation").inc(dropped)
        TIMELINE.record("host", "cache.evict",
                        attrs={"cause": "index_invalidation",
                               "entries": dropped})

    def _confirm_plan(self, slot: int) -> None:
        """The slot's prefill is enqueued: its registrations are
        backed by real (dispatched) writes."""
        if self.block_size is not None:
            with self._block_lock:
                self._plan_regs.pop(slot, None)

    def _schedule_block_release(self, slot: int) -> None:
        """Queue a slot's blocks for release.  Release is DEFERRED by
        pipeline_depth waves: dispatches already in flight carry the
        old device table and keep garbage-writing the dead slot's
        tail blocks — releasing (and possibly reallocating) those
        blocks inside that window would let a zombie wave corrupt
        another request's cache."""
        if self.block_size is None:
            return
        with self._block_lock:
            blocks = [int(b) for b in self._tables[slot] if b >= 0]
            self._tables[slot, :] = -1
        if blocks:
            self._deferred_frees.append(
                (self.decode_steps + self.pipeline_depth + 1, blocks))

    def _process_deferred_frees(self, force: bool = False) -> None:
        if self.block_size is None:
            return
        released = 0
        while self._deferred_frees and (
                force or self._deferred_frees[0][0] <= self.decode_steps):
            _, blocks = self._deferred_frees.popleft()
            released += len(blocks)
            with self._block_lock:
                for blk in blocks:
                    self._unref_block_locked(blk)
        if released:
            # The normal release path: every slot block matures through
            # the zombie-wave deferral window exactly once.
            self.block_evictions["zombie_deferral"] += released
            obs.generator_block_evictions_total().labels(
                model=self.name, cause="zombie_deferral").inc(released)

    # -- host KV tier: spill & fault-back ----------------------------------
    # Both paths ride the single-worker enqueue executor, whose only
    # submitter is the scheduler loop: submission FIFO there IS device
    # program order, so a gather dispatched before an overwriting
    # insert snapshots pre-overwrite bytes (the XLA data dependency
    # pins them) no matter when its D2H fetch completes, and a
    # fault-back insert dispatched before the plan's own prefill is
    # resident by the time anything reads the block.

    def _drain_spills(self) -> None:
        """Runs on the ENQUEUE executor, before any dispatch that
        could rewrite a spill-pending block: one non-donating gather
        dispatch per <=32-block group snapshots the pending blocks'
        k/v, then the fetch executor D2Hs the snapshot and writes the
        tier — the scheduler loop never touches mmap I/O."""
        if self.kv_tier is None:
            return
        with self._block_lock:
            if not self._spill_pending:
                return
            pending = self._spill_pending
            self._spill_pending = []
        jnp = self._jnp
        for i in range(0, len(pending), 32):
            grp = pending[i:i + 32]
            padded = 1
            while padded < len(grp):
                padded *= 2
            # Pad to a pow2 gather width (bounded compile count, same
            # discipline as prefill row buckets); pad rows duplicate
            # block 0 and are simply not written to the tier.
            idx = np.asarray(
                [b for _, b in grp]
                + [grp[0][1]] * (padded - len(grp)), np.int32)
            self._note_program("kv_gather", padded)
            snap = self._gather_blocks(self._caches, jnp.asarray(idx))
            self._executor.submit(self._spill_write, grp, snap)

    def _spill_write(self, grp: List[Tuple[bytes, int]], snap) -> None:
        """Fetch-executor side of a spill: D2H the gathered snapshot
        (a sanctioned sync, same contract as wave fetches) and write
        each block's payload into the host tier.  TRANSACTIONAL per
        block: any failure — the `engine.kv_spill` chaos site, a full
        tier, an mmap error — degrades THAT eviction to the
        drop-on-evict baseline, and the tier index only publishes
        after the full payload landed, so a half-spilled chain is
        never readable.  The eviction-cause accounting deferred at
        `_alloc_block_locked` lands here: capacity_spilled when the
        tier committed, capacity_dropped otherwise — the split stays
        honest under chaos."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        outcomes: List[Tuple[int, str]] = []
        try:
            if faults.configured(fault_sites.ENGINE_KV_SPILL):
                faults.inject_sync(fault_sites.ENGINE_KV_SPILL,
                                   key=self.name)
            with sanitizer.sanctioned_fetch():
                # kfslint: disable=host-sync — sanctioned fetch site:
                # the spill snapshot's D2H join, off-loop on the fetch
                # executor.
                host = [(np.asarray(k), np.asarray(v))
                        for k, v in snap]
            for row, (chain, blk) in enumerate(grp):
                payload = b"".join(
                    part for k, v in host
                    for part in (k[row].tobytes(), v[row].tobytes()))
                ok = self.kv_tier.put(chain, payload)
                outcomes.append((blk, "capacity_spilled" if ok
                                 else "capacity_dropped"))
        except FaultInjected:
            pass  # chaos: remaining blocks degrade to drops below
        except Exception:
            logger.exception("kv spill batch failed")
        finally:
            aborted = len(grp) - len(outcomes)
            if aborted:
                self.kv_tier.note_spill_failure(aborted)
                outcomes.extend(
                    (blk, "capacity_dropped")
                    for _, blk in grp[len(outcomes):])
            with self._block_lock:
                for blk, cause in outcomes:
                    self._count_capacity_locked(cause, blk)

    def _drain_faultbacks(self) -> bool:
        """Runs on the ENQUEUE executor, after planning and before the
        plan's own dispatches: read every pending primary fault-back's
        payload from the host tier and land it in the pool with one
        insert dispatch per <=32-block group.  Returns False on ANY
        failure (the `engine.kv_faultback` chaos site, an entry
        evicted between probe and read, a read error) WITHOUT having
        dispatched anything — the caller rolls the whole plan set back
        and the requests re-admit as plain re-prefills (the chains are
        dropped from the tier, so the replan misses it: transactional
        degradation).  Spills drain FIRST: this very plan's fresh
        dest allocations may have evicted spill-pending blocks, and
        their gather must dispatch before the insert overwrites
        them."""
        self._drain_spills()
        if self.kv_tier is None:
            return True
        with self._block_lock:
            if not self._faultback_pending:
                return True
            pending = self._faultback_pending
            self._faultback_pending = []
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        primaries = [(ch, blk) for ch, blk, _r, prim in pending
                     if prim]
        riders = len(pending) - len(primaries)
        t0 = time.perf_counter()
        payloads: Dict[bytes, bytes] = {}
        try:
            if faults.configured(fault_sites.ENGINE_KV_FAULTBACK):
                faults.inject_sync(fault_sites.ENGINE_KV_FAULTBACK,
                                   key=self.name)
            for ch, _blk in primaries:
                payloads[ch] = self.kv_tier.read(ch)
        except Exception as e:
            # Transactional failure: nothing dispatched, no index
            # entry published.  Drop the chains (their payloads are
            # now suspect / proven unreadable) so the replanned turns
            # MISS the tier and re-prefill from the prompt.
            if not isinstance(e, (FaultInjected, KeyError)):
                logger.warning("kv fault-back failed: %r", e)
            self.kv_tier.note_fault_failure(len(pending))
            with self._block_lock:
                for ch, _blk in primaries:
                    self._faultback_by_chain.pop(ch, None)
            for ch, _blk in primaries:
                self.kv_tier.drop(ch)
                self.kv_tier.end_fault(ch)
            return False
        # Payloads in hand: land them with the same insert program
        # prefill uses (B=1 row, -1 pads drop), then publish the
        # chains to the prefix index — from here the blocks are
        # ordinary shareable device-resident prefix state.
        jnp = self._jnp
        k0 = self._caches[0][0]
        bs, H, D = (int(x) for x in k0.shape[1:])
        dtype = np.dtype(k0.dtype)
        per = bs * H * D * dtype.itemsize
        for i in range(0, len(primaries), 32):
            grp = primaries[i:i + 32]
            padded = 1
            while padded < len(grp):
                padded *= 2
            layers = [(np.zeros((1, padded * bs, H, D), dtype),
                       np.zeros((1, padded * bs, H, D), dtype))
                      for _ in self._caches]
            dest = np.full((1, padded), -1, np.int32)
            for j, (ch, blk) in enumerate(grp):
                pay = payloads[ch]
                dest[0, j] = blk
                for li, (k_new, v_new) in enumerate(layers):
                    off = li * 2 * per
                    k_new[0, j * bs:(j + 1) * bs] = np.frombuffer(
                        pay, dtype, count=bs * H * D,
                        offset=off).reshape(bs, H, D)
                    v_new[0, j * bs:(j + 1) * bs] = np.frombuffer(
                        pay, dtype, count=bs * H * D,
                        offset=off + per).reshape(bs, H, D)
            self._note_program("kv_faultback", padded)
            self._caches = self._insert(
                self._caches,
                [(jnp.asarray(k), jnp.asarray(v)) for k, v in layers],
                jnp.asarray(dest))
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        saved = 0
        with self._block_lock:
            for ch, blk in primaries:
                # Publish: the insert is dispatched, so the block is
                # ordinary prefix state.  A concurrent identical
                # admission may have registered the chain first —
                # keep the canonical entry (same rule as
                # _register_chunk_blocks); our block stays private.
                if self._prefix_index.get(ch) is None:
                    self._prefix_index[ch] = blk
                    self._block_chain[blk] = ch
                self._faultback_by_chain.pop(ch, None)
            for _ch, _blk, req, _prim in pending:
                req.host_tier_hit_blocks += 1
                req.host_tier_saved_tokens += self.block_size
                saved += self.block_size
            self.host_tier_tokens_saved += saved
        for ch, _blk in primaries:
            self.kv_tier.end_fault(ch)
        obs.generator_kv_tier_tokens_saved_total().labels(
            model=self.name).inc(saved)
        self.kv_tier.note_faultback(len(primaries), elapsed_ms)
        if riders:
            self.kv_tier.note_coalesced(riders)
        TIMELINE.record("host", "cache.faultback",
                        attrs={"blocks": len(primaries),
                               "coalesced": riders,
                               "ms": round(elapsed_ms, 3)})
        return True

    # -- durable handoff (ISSUE 19): drain parachute & peer import ---------
    def export_kv(self, budget_s: float = 2.0) -> Dict[str, int]:
        """Drain parachute: export live slots' device KV blocks plus
        the hot prefix-index chains into the host tier under a bounded
        budget, so a successor process (or a peer pulling over
        /kv/chains) can serve the returning conversations as warm
        fault-backs instead of full re-prefills.

        BLOCKING — call off the event loop (the server wraps it in
        run_in_executor on the SIGTERM/announce_swap drain path).  The
        worker rides the single-worker enqueue executor so its gather
        dispatches are FIFO-ordered against any still-inflight wave
        enqueues (the same ordering proof as `_drain_spills`).
        Deadline-aware: candidates are ordered hottest-first (live
        slots, then prefix chains by reuse depth) and whatever the
        budget cannot cover is counted dropped — the export never
        stretches the swap window."""
        zeros = {"exported": 0, "skipped": 0, "dropped": 0,
                 "failed": 0}
        if self.block_size is None or self.kv_tier is None:
            return zeros
        deadline = time.monotonic() + max(0.0, float(budget_s))
        try:
            fut = self._enqueue_executor.submit(
                self._export_kv_worker, deadline)
        except RuntimeError:
            return zeros  # executor already shut down
        return fut.result()

    def _export_kv_worker(self, deadline: float) -> Dict[str, int]:
        """ENQUEUE-executor side of the drain parachute.  Candidate
        order is the eviction-value order: live slots first (the
        conversation is literally mid-flight — its return is the most
        certain), then registered prefix chains hottest-first by
        reuse depth.  TRANSACTIONAL per block: the tier index only
        publishes complete digest-recorded payloads, and the
        `engine.kv_export` chaos site fails the whole pass BEFORE any
        tier write (every candidate counted outcome=failed — the
        drain degrades to the no-handoff baseline)."""
        import hashlib

        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        out = {"exported": 0, "skipped": 0, "dropped": 0, "failed": 0}
        t0 = time.perf_counter()
        bs = self.block_size
        cand: List[Tuple[bytes, int]] = []
        seen: set = set()
        with self._block_lock:
            for si, s in enumerate(self._slots):
                if s is None or s.prefilling:
                    continue
                ids = s.req.prompt_ids
                n = int(ids.size)
                ext = max(0, int(s.length) - n)
                if ext > 0 and s.tokens:
                    # The return visit's prompt extends prompt+output,
                    # so chains over the CONCATENATION are what its
                    # plan will probe (same int32 bytes _submit
                    # normalizes to).
                    allids = np.concatenate(
                        [ids, np.asarray(s.tokens[:ext], np.int32)])
                else:
                    allids = ids
                full = min(int(s.length), int(allids.size)) // bs
                chain = b""
                for c in range(full):
                    chain = hashlib.blake2b(
                        chain
                        + allids[c * bs:(c + 1) * bs].tobytes(),
                        digest_size=16).digest()
                    blk = int(self._tables[si, c])
                    if blk < 0 or chain in seen:
                        continue
                    seen.add(chain)
                    if self.kv_tier.contains(chain):
                        out["skipped"] += 1
                        continue
                    cand.append((chain, blk))
            hot = sorted(
                ((self._chain_hits.get(ch, 0), ch, blk)
                 for ch, blk in self._prefix_index.items()
                 if ch not in seen),
                key=lambda t: t[0], reverse=True)
            for _depth, ch, blk in hot:
                seen.add(ch)
                if self.kv_tier.contains(ch):
                    out["skipped"] += 1
                    continue
                cand.append((ch, blk))
        try:
            if cand and faults.configured(fault_sites.ENGINE_KV_EXPORT):
                faults.inject_sync(fault_sites.ENGINE_KV_EXPORT,
                                   key=self.name)
        except FaultInjected:
            # Chaos: the whole pass fails BEFORE any tier write.
            out["failed"] = len(cand)
            cand = []
        jnp = self._jnp
        for i in range(0, len(cand), 32):
            if time.monotonic() >= deadline:
                # Budget exhausted: the remaining (coldest) tail is
                # dropped, honestly counted — never stall the swap.
                out["dropped"] += len(cand) - i
                break
            grp = cand[i:i + 32]
            padded = 1
            while padded < len(grp):
                padded *= 2
            idx = np.asarray(
                [b for _, b in grp]
                + [grp[0][1]] * (padded - len(grp)), np.int32)
            try:
                self._note_program("kv_gather", padded)
                snap = self._gather_blocks(self._caches,
                                           jnp.asarray(idx))
                with sanitizer.sanctioned_fetch():
                    # kfslint: disable=host-sync — sanctioned fetch
                    # site: the drain parachute's D2H join, off-loop
                    # on the enqueue executor during the swap window.
                    host = [(np.asarray(k), np.asarray(v))
                            for k, v in snap]
            except Exception:
                logger.exception("kv export gather failed")
                out["failed"] += len(grp)
                continue
            for row, (chain, _blk) in enumerate(grp):
                payload = b"".join(
                    part for k, v in host
                    for part in (k[row].tobytes(), v[row].tobytes()))
                out["exported" if self.kv_tier.put(chain, payload)
                    else "failed"] += 1
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        for outcome, count in out.items():
            if count:
                obs.kv_handoff_exported_blocks_total().labels(
                    model=self.name, outcome=outcome).inc(count)
        obs.kv_handoff_export_ms().labels(
            model=self.name).observe(elapsed_ms)
        TIMELINE.record("host", "kv.export",
                        attrs={**out, "ms": round(elapsed_ms, 3)})
        if any(out.values()):
            logger.info(
                "kv export (%s): exported=%d skipped=%d dropped=%d "
                "failed=%d in %.1fms", self.name, out["exported"],
                out["skipped"], out["dropped"], out["failed"],
                elapsed_ms)
        return out

    def kv_import(self, pairs: List[Tuple[bytes, bytes]]
                  ) -> Dict[str, int]:
        """Admit peer-transferred (chain, payload) pairs into the host
        tier (the /kv/reattach pull path; payloads were already
        digest-verified against the wire header by the server).
        BLOCKING but dispatch-free — plain tier writes, safe from any
        executor thread.  TRANSACTIONAL: the `engine.kv_import` chaos
        site rejects the whole batch BEFORE any tier publication
        (every pair counted outcome=failed), so a failed import
        leaves the tier untouched and the returning turn degrades to
        a clean re-prefill."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        out = {"imported": 0, "skipped": 0, "failed": 0}
        if self.block_size is None or self.kv_tier is None or \
                not pairs:
            return out
        try:
            if faults.configured(fault_sites.ENGINE_KV_IMPORT):
                faults.inject_sync(fault_sites.ENGINE_KV_IMPORT,
                                   key=self.name)
        except FaultInjected:
            out["failed"] = len(pairs)
            obs.kv_handoff_peer_blocks_total().labels(
                model=self.name, outcome="failed").inc(len(pairs))
            return out
        for chain, payload in pairs:
            if self.kv_tier.contains(chain):
                out["skipped"] += 1
                continue
            out["imported" if self.kv_tier.put(chain, payload)
                else "failed"] += 1
        for outcome, count in out.items():
            if count:
                obs.kv_handoff_peer_blocks_total().labels(
                    model=self.name, outcome=outcome).inc(count)
        TIMELINE.record("host", "kv.import", attrs=dict(out))
        return out

    def _plan_prompt_blocks(self, req: _Request, slot: int,
                            chunk_regs: Optional[Dict[int, Tuple[
                                bytes, int]]] = None,
                            force_miss: bool = False
                            ) -> Optional[List[int]]:
        """Allocate/share blocks for a prompt (loop thread, pre-
        enqueue).  Full chunks probe the prefix index by chain hash —
        causal attention makes k/v for positions [0, m) a pure
        function of the first m tokens, so chunks whose whole-prefix
        chain matches can point at existing blocks instead of storing
        copies.  Returns the per-chunk dest list for the insert
        scatter (-1 = shared hit, write dropped), or None when the
        pool cannot satisfy the request right now (caller leaves it
        pending).

        force_miss (the `generator.prefix_lookup` chaos site, probed
        async by the scheduler loop): skip every index probe — a
        cache-miss storm on demand, which the lookup telemetry must
        count as misses.

        chunk_regs (chunked-prefill admissions): fresh full-block
        registrations land in this dict keyed by block index INSTEAD
        of the prefix index — a chunked prompt's later blocks are
        written by chunk dispatches that may be many waves in the
        future, and registering them now would let a sharer's decode
        read a block no dispatch has been enqueued for yet.  The
        scheduler registers each chunk's blocks when that chunk's
        dispatch enqueues."""
        import hashlib

        bs = self.block_size
        n = int(req.prompt_ids.size)
        full = n // bs
        total = (n + bs - 1) // bs
        dest: List[int] = []
        taken: List[int] = []
        fresh_regs: List[Tuple[bytes, int]] = []
        # Plan-local lookup accounting, flushed to the registry twins
        # outside the block lock (one .labels() resolve per plan, not
        # per block); hit_chains lets the rollback path rewind the
        # reuse-depth census it provisionally advanced.
        plan_hits = 0
        plan_misses = 0
        hit_chains: List[bytes] = []
        depth_obs: List[int] = []
        # Host-tier fault-backs this plan claims: (chain, dest block,
        # primary).  primary=False rows coalesce on a pending fault's
        # block instead of reading the tier again (single-flight).
        plan_host_hits = 0
        host_faults: List[Tuple[bytes, int, bool]] = []
        # Chain digests depend only on the prompt bytes — compute them
        # outside the lock, once, for both the hit probe and the
        # allocation loop below.
        chains: List[bytes] = []
        chain = b""
        for c in range(full):
            chain = hashlib.blake2b(
                chain + req.prompt_ids[c * bs:(c + 1) * bs].tobytes(),
                digest_size=16).digest()
            chains.append(chain)
        with self._block_lock:
            max_hit_blocks = None
            if chunk_regs is not None:
                # Chunk dispatches write EVERY position of their chunk
                # through the slot's table — unlike paged_insert there
                # is no per-block drop mask, so a chunk mixing shared
                # (prefix-hit) and fresh blocks would REWRITE the
                # shared blocks with a different compiled program's
                # (not bit-identical) k/v under a live sharer's reads.
                # Accept hits only as a contiguous prefix rounded DOWN
                # to whole chunks, and never into the final chunk
                # (which always dispatches to sample the first token):
                # all-hit chunks skip their dispatch outright, so the
                # shared blocks they cover are never written.  The
                # probe runs under the SAME lock hold as the
                # allocation loop below — an eviction between the two
                # could otherwise punch a hole in the counted prefix.
                bpc = self.prefill_chunk_tokens // bs
                h = 0
                for c in range(full):
                    if force_miss:
                        break
                    if self._prefix_index.get(chains[c]) is not None:
                        h += 1
                        continue
                    # Probe order: device index above, host tier
                    # here — a host-resident chain counts toward the
                    # contiguous hit prefix (its chunk skips dispatch
                    # after the fault-back lands), re-prefill below.
                    if self.kv_tier is not None and (
                            chains[c] in self._faultback_by_chain
                            or self.kv_tier.contains(chains[c])):
                        h += 1
                        continue
                    break
                n_chunks = -(-n // self.prefill_chunk_tokens)
                max_hit_blocks = min((h // bpc) * bpc,
                                     bpc * (n_chunks - 1))
            for c in range(total):
                host_chain: Optional[bytes] = None
                if c < full:
                    chain = chains[c]
                    hit = (None if force_miss
                           else self._prefix_index.get(chain))
                    if hit is not None and (max_hit_blocks is None
                                            or c < max_hit_blocks):
                        self._ref_block_locked(hit)
                        self._tables[slot, c] = hit
                        taken.append(hit)
                        dest.append(-1)
                        self.prefix_hits += 1
                        plan_hits += 1
                        hit_chains.append(chain)
                        depth = self._chain_hits.get(chain, 0) + 1
                        self._chain_hits[chain] = depth
                        depth_obs.append(depth)
                        continue
                    # Device miss: probe the host tier (probe order
                    # device -> host tier -> re-prefill).  Chunked
                    # plans only accept host hits inside the whole-
                    # chunk hit prefix — exactly where a device hit
                    # would be accepted — because a dispatching chunk
                    # rewrites EVERY block it covers and a fault-back-
                    # registered block may already be shared.
                    if (self.kv_tier is not None and not force_miss
                            and (max_hit_blocks is None
                                 or c < max_hit_blocks)):
                        shared = self._faultback_by_chain.get(chain)
                        if shared is not None:
                            # Single-flight: a pending (undrained)
                            # fault-back already targets this chain —
                            # ride its block instead of reading the
                            # tier twice.
                            self._ref_block_locked(shared)
                            self._tables[slot, c] = shared
                            taken.append(shared)
                            dest.append(-1)
                            plan_host_hits += 1
                            host_faults.append((chain, shared, False))
                            continue
                        if self.kv_tier.begin_fault(chain):
                            host_chain = chain
                blk = self._alloc_block_locked()
                if blk is None and host_chain is not None:
                    self.kv_tier.end_fault(host_chain)
                if blk is None:
                    # Roll back: this request waits for freed blocks.
                    # Deregister THIS plan's fresh registrations
                    # first — their blocks were never written, and a
                    # later plan hitting a stale chain would share
                    # all-zero k/v (code-review r5).
                    dropped = 0
                    for ch, b in fresh_regs:
                        if self._prefix_index.pop(ch, None) is not None:
                            dropped += 1
                            self._chain_hits.pop(ch, None)
                        self._block_chain.pop(b, None)
                    self._count_invalidations_locked(dropped)
                    for b in taken:
                        self._unref_block_locked(b)
                    # Release this plan's host-tier claims: primaries
                    # unpin their tier entries (eviction may take them
                    # again) and unpublish the coalescing point; the
                    # replan re-probes the tier from scratch.
                    for ch, _b, primary in host_faults:
                        if primary:
                            self.kv_tier.end_fault(ch)
                            self._faultback_by_chain.pop(ch, None)
                    # Rewind the reuse-depth census: the replan will
                    # re-probe these chains and count them again.
                    for ch in hit_chains:
                        d = self._chain_hits.get(ch)
                        if d is not None:
                            if d <= 1:
                                self._chain_hits.pop(ch, None)
                            else:
                                self._chain_hits[ch] = d - 1
                    self._tables[slot, :] = -1
                    self._flush_lookup_counters(
                        req, None, plan_hits, plan_misses, depth_obs,
                        plan_host_hits=plan_host_hits)
                    return None
                self._ref_block_locked(blk)
                self._tables[slot, c] = blk
                taken.append(blk)
                if host_chain is not None:
                    # Fault-back: the host tier holds this chain's
                    # k/v.  The drain (enqueue executor, FIFO-before
                    # any dispatch that could read the block) inserts
                    # it into `blk`; the plan treats the block as a
                    # hit — dest -1 drops the prefill's own write, and
                    # an all-hit chunk skips its dispatch outright
                    # (the compute saving fault-back exists for).
                    dest.append(-1)
                    plan_host_hits += 1
                    host_faults.append((host_chain, blk, True))
                    self._faultback_by_chain[host_chain] = blk
                    continue
                dest.append(blk)
                if c < full:
                    plan_misses += 1
                    # Freshly written FULL prompt blocks become
                    # shareable (they are never written again: decode
                    # writes land past the prompt).  PROVISIONAL until
                    # the prefill actually enqueues — an enqueue
                    # failure must deregister them.
                    self.prefix_misses += 1
                    if chunk_regs is not None:
                        # A demoted hit (the chain already maps — its
                        # block just wasn't acceptable above) keeps the
                        # canonical index entry; registering this
                        # recompute would churn sharers onto a
                        # duplicate block for no gain.
                        if self._prefix_index.get(chain) is None:
                            chunk_regs[c] = (chain, blk)
                    else:
                        self._prefix_index[chain] = blk
                        self._block_chain[blk] = chain
                        fresh_regs.append((chain, blk))
            if chunk_regs is None:
                self._plan_regs[slot] = fresh_regs
            if host_faults:
                # Claimed under the lock; the caller MUST drain these
                # (one tier read + one pool insert dispatch on the
                # enqueue executor) before any dispatch of this plan
                # can read the blocks, and roll the whole plan back if
                # the drain fails.
                for ch, b, primary in host_faults:
                    self._faultback_pending.append((ch, b, req,
                                                    primary))
        self._flush_lookup_counters(req, dest, plan_hits, plan_misses,
                                    depth_obs,
                                    plan_host_hits=plan_host_hits)
        return dest

    def _flush_lookup_counters(self, req: _Request,
                               dest: Optional[List[int]],
                               plan_hits: int, plan_misses: int,
                               depth_obs: List[int],
                               plan_host_hits: int = 0) -> None:
        """Flush one plan's lookup accounting to the registry twins
        (one family resolve per plan, outside the per-block loop) and,
        on a successful plan, fold the cache economics into the
        request's cost record and the timeline."""
        if plan_hits:
            obs.generator_prefix_lookups_total().labels(
                model=self.name, outcome="hit").inc(plan_hits)
            fam = obs.generator_prefix_reuse_depth_hits()
            for depth in depth_obs:
                fam.labels(model=self.name).observe(depth)
        if plan_host_hits:
            # Device miss answered by the host tier: counted as its
            # own lookup outcome (token-saved attribution waits for
            # the fault-back to actually COMMIT on the drain — a
            # chaos-failed fault-back re-prefills and saves nothing).
            obs.generator_prefix_lookups_total().labels(
                model=self.name, outcome="host_hit").inc(
                    plan_host_hits)
        if plan_misses:
            obs.generator_prefix_lookups_total().labels(
                model=self.name, outcome="miss").inc(plan_misses)
        if dest is None:
            return
        req.blocks_held = max(req.blocks_held, len(dest))
        if plan_hits:
            saved = plan_hits * self.block_size
            self.prefill_tokens_saved += saved
            req.cache_hit_blocks += plan_hits
            req.cache_saved_tokens += saved
            obs.generator_prefill_tokens_saved_total().labels(
                model=self.name).inc(saved)
            TIMELINE.record("host", "cache.hit",
                            trace_id=req.trace_id,
                            attrs={"blocks": plan_hits,
                                   "tokens_saved": saved})

    def _ensure_block_capacity(self) -> List[int]:
        """Grow active slots' tables to cover the next
        pipeline_depth * K decode steps (device positions run ahead
        of the host by up to that).  Returns slots that could not
        grow — the caller fails those requests."""
        if self.block_size is None:
            return []
        bs = self.block_size
        horizon = self.steps_per_call * self.pipeline_depth + 1
        if self.spec_tokens:
            # A spec wave writes K+1 positions past the host length in
            # one dispatch (spec runs depth-1, but the widest single
            # dispatch sets the write horizon).
            horizon = max(horizon, self.spec_tokens + 2)
        failed: List[int] = []
        with self._block_lock:
            for i, s in enumerate(self._slots):
                if s is None or s.prefilling:
                    # Mid-chunked-prefill slots hold their whole
                    # prompt's blocks already and decode nothing —
                    # growth starts when the final chunk lands.
                    continue
                need = min((s.length + horizon + bs - 1) // bs,
                           self.blocks_per_slot)
                cur = int(np.sum(self._tables[i] >= 0))
                ok = True
                grown = cur
                for c in range(cur, need):
                    blk = self._alloc_block_locked()
                    if blk is None:
                        ok = False
                        break
                    self._ref_block_locked(blk)
                    self._tables[i, c] = blk
                    grown = c + 1
                # Peak residency for the cost record: grown starts at
                # cur and only increases, so it IS the table's block
                # count for this stream now.
                s.req.blocks_held = max(s.req.blocks_held, grown)
                if not ok:
                    failed.append(i)
        return failed

    def _table_device(self):
        """Device copy of the block tables for a dispatch (dense mode:
        a dummy — the jitted program ignores it)."""
        jnp = self._jnp
        if self.block_size is None:
            return jnp.zeros((1,), jnp.int32)
        with self._block_lock:
            # Copy under the lock: cancel() clears rows on the loop
            # thread while waves enqueue on the enqueue thread.
            snap = self._tables.copy()
        return jnp.asarray(snap)

    def _record_pool_sample(self) -> None:
        """Occupancy counter sample for the event timeline (rendered
        as Chrome counter tracks).  Lock-free reads: len() under the
        GIL is atomic and a stale-by-one sample is fine for a
        telemetry series."""
        values = {
            "active_slots": sum(1 for s in self._slots
                                if s is not None),
            "pending": len(self._pending),
            # String attr: the Chrome exporter drops non-numerics from
            # counter series, but multi-engine consumers (the bench
            # cache summary) need to know WHOSE pool a sample
            # describes — untagged samples would blend two engines'
            # pools into one meaningless ratio.
            "engine": self.name,
        }
        if self.block_size is not None:
            values["free_blocks"] = len(self._free_blocks)
            values["reclaimable_blocks"] = len(self._reclaimable)
        TIMELINE.counter("pool", values)

    # -- scheduler ---------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    async def _run(self):
        try:
            await self._run_inner()
        except Exception as e:  # decode/device failure: global
            logger.exception("generation scheduler failed")
            self._fail_all(f"error: generation failed: {e}")
        finally:
            # A close()/unload() with work in flight must not strand
            # awaiters on queues that will never receive a terminal
            # event.
            if self._closed:
                self._fail_all("error: generator closed")

    def _fail_all(self, reason: str):
        for i, s in enumerate(self._slots):
            if s is not None:
                s.req.out.put_nowait((None, reason))
                self._free_slot_state(i)
        while self._pending:
            self._pending.popleft().out.put_nowait((None, reason))

    def _bucket_for(self, n: int) -> int:
        return next(b for b in self.prefill_buckets if b >= n)

    def _set_hold(self, held: bool) -> None:
        """Track growth-starvation HOLD transitions: the window from
        the first held iteration to the release is one host-track
        timeline span — the stall a pinned p99 outlier (or a bench
        summary) can attribute instead of inferring."""
        self._growth_starved = held
        if held:
            if self._hold_since is None:
                self._hold_since = time.time()
        elif self._hold_since is not None:
            now = time.time()
            TIMELINE.record("host", "hold", dur_s=now - self._hold_since,
                            t_end=now)
            self._hold_since = None

    async def _probe_prefix_fault(self) -> bool:
        """The `generator.prefix_lookup` chaos site, probed ON the
        loop (async sleeps for injected latency — never a blocking
        sleep on the scheduler): an injected error forces the next
        admission's plan to MISS the whole prefix index, a cache-miss
        storm on demand whose misses the lookup telemetry must count.
        configured() keeps the no-faults hot path at one dict
        lookup."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        if not faults.configured(fault_sites.GENERATOR_PREFIX_LOOKUP):
            return False
        try:
            await faults.inject(fault_sites.GENERATOR_PREFIX_LOOKUP,
                                key=self.name)
        except FaultInjected:
            return True
        return False

    def _take_prefill_group(self, force_miss: bool = False):
        """Pop the front run of pending requests that share a prefill
        bucket, up to the free slot count — they ride ONE prefill
        dispatch.  Strict FIFO: a different-bucket request at the front
        is never jumped.  In paged mode each taken request's prompt
        blocks are planned (allocated/prefix-shared) HERE on the loop
        thread; a request the pool cannot hold yet stays pending (it
        admits when slots release blocks).  Returns
        (group, slots, bucket, dest_rows) — dest_rows is None for
        dense mode."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        group: List[_Request] = []
        bucket = 0
        dest_rows: Optional[List[List[int]]] = (
            [] if self.block_size is not None else None)
        while self._pending and len(group) < len(free):
            if self._is_cold(self._pending[0]):
                break  # cold prompts take the chunked path
            b = self._bucket_for(self._pending[0].prompt_ids.size)
            if not group:
                bucket = b
            elif b != bucket:
                break
            if dest_rows is not None:
                plan = self._plan_prompt_blocks(self._pending[0],
                                                free[len(group)],
                                                force_miss=force_miss)
                if plan is None:
                    break  # pool pressure: wait for released blocks
                dest_rows.append(plan)
            group.append(self._pending.popleft())
        return group, free[:len(group)], bucket, dest_rows

    # -- chunked prefill ---------------------------------------------------
    # A COLD prompt (longer than prefill_chunk_tokens, paged mode)
    # lands in fixed-width, block-aligned chunks that ride the same
    # in-flight FIFO as decode waves — the scheduler alternates chunk
    # and wave dispatches, so live streams stall per-chunk instead of
    # per-prompt.  Carried state: the slot's block table holds every
    # written position's k/v (cross-chunk attention reads it exactly
    # like decode), the next chunk index lives on the _Active, and the
    # final chunk samples the stream's first token on device.

    def _is_cold(self, req: _Request) -> bool:
        return (self.prefill_chunk_tokens is not None
                and int(req.prompt_ids.size) > self.prefill_chunk_tokens)

    def _chunk_shared(self, act: _Active, idx: int) -> bool:
        """True when every block of chunk `idx` was a prefix-cache
        hit — the pool already holds its k/v, so the chunk's dispatch
        can be skipped outright (the monolithic path recomputes and
        drops the writes; chunking turns the hit into saved FLOPs)."""
        bpc = self.prefill_chunk_tokens // self.block_size
        lo = idx * bpc
        hi = min(lo + bpc, len(act.chunk_dest))
        return all(act.chunk_dest[c] == -1 for c in range(lo, hi))

    async def _admit_chunked(self, loop, inflight: deque,
                             force_miss: bool = False) -> bool:
        """Admit the front pending (cold) request onto a free slot in
        chunked mode: plan ALL prompt blocks now (prefix hits share;
        registration of fresh blocks is deferred per chunk), install
        the slot as `prefilling`, and dispatch the first chunk.
        Returns False on pool pressure — the request stays pending."""
        slot = self._free_slot()
        req = self._pending[0]
        chunk_regs: Dict[int, Tuple[bytes, int]] = {}
        dest = self._plan_prompt_blocks(req, slot,
                                        chunk_regs=chunk_regs,
                                        force_miss=force_miss)
        if dest is None:
            return False
        if (self.kv_tier is not None and self._faultback_pending
                and not await loop.run_in_executor(
                    self._enqueue_executor, self._drain_faultbacks)):
            # Transactional fault-back failure: nothing dispatched —
            # release this plan's blocks (its fresh registrations were
            # deferred into chunk_regs and never published) and leave
            # the request pending.  The immediate replan misses the
            # tier (failed chains dropped) and re-prefills.
            self._schedule_block_release(slot)
            return True
        self._pending.popleft()
        n = int(req.prompt_ids.size)
        act = _Active(req=req, length=n, last_token=-1, generated=0,
                      prefilling=True,
                      chunk_total=-(-n // self.prefill_chunk_tokens),
                      chunk_dest=dest, chunk_regs=chunk_regs)
        self._slots[slot] = act
        self.chunked_admissions += 1
        await self._step_chunk(loop, inflight, slot, act)
        return True

    async def _step_chunk(self, loop, inflight: deque, slot: int,
                          act: _Active) -> None:
        """Dispatch the next chunk of a mid-prefill slot into the
        in-flight FIFO.  Chunks whose every block was a prefix hit are
        skipped (except the final one — it must run to sample the
        first token)."""
        idx = act.chunk_next
        # kfslint: disable=spin-loop — bounded by chunk_total (each
        # pass increments idx); no external coroutine gates the exit.
        while idx < act.chunk_total - 1 and self._chunk_shared(act,
                                                               idx):
            self.prefill_chunks_skipped += 1
            obs.generator_prefill_chunks_total().labels(
                outcome="skipped_shared").inc()
            idx += 1
        final = idx >= act.chunk_total - 1
        act.chunk_next = idx + 1
        try:
            firsts_h, lp_h = await loop.run_in_executor(
                self._enqueue_executor, self._enqueue_chunk,
                slot, act, idx, final)
        except Exception as e:
            # Same contract as a monolithic prefill enqueue failure:
            # fail THIS request, release its blocks (deferred), keep
            # everything else decoding.  Deferred registrations were
            # never published, so no stale chain can alias.
            logger.exception("chunk-prefill enqueue failed")
            if self._slots[slot] is act:
                self._free_slot_state(slot)
                act.req.out.put_nowait(
                    (None, f"error: prefill failed: {e}"))
            return
        if self._slots[slot] is act:
            # Fresh blocks of THIS chunk are now backed by a
            # dispatched write: publish them to the prefix index
            # (a cancel during the enqueue released the blocks — a
            # publish then would alias a future occupant's data).
            self._register_chunk_blocks(act, idx)
            if final:
                # The first token is in the device feed arrays: waves
                # enqueued from here on decode this slot for real.
                act.prefilling = False
        self.prefill_chunks += 1
        obs.generator_prefill_chunks_total().labels(
            outcome="dispatched").inc()
        act.chunks_inflight += 1
        fut = loop.run_in_executor(self._executor, self._fetch_wave,
                                   firsts_h, lp_h)
        inflight.append(("chunk", fut, (slot, act, idx, final),
                         time.perf_counter()))

    def _register_chunk_blocks(self, act: _Active, idx: int) -> None:
        if not act.chunk_regs:
            return
        bpc = self.prefill_chunk_tokens // self.block_size
        lo = idx * bpc
        hi = min(lo + bpc, len(act.chunk_dest))
        with self._block_lock:
            for c in range(lo, hi):
                reg = act.chunk_regs.pop(c, None)
                if reg is None:
                    continue
                chain, blk = reg
                if self._prefix_index.get(chain) is not None:
                    # A concurrent identical admission registered this
                    # chain first (both planned before either's chunk
                    # dispatched, so both allocated fresh blocks).
                    # Keep the canonical entry: overwriting would leave
                    # the first block's _block_chain mapping orphaned,
                    # and its eventual eviction used to delete the
                    # survivor's index entry.  Our block stays private
                    # and frees normally.
                    continue
                self._prefix_index[chain] = blk
                self._block_chain[blk] = chain

    def _enqueue_chunk(self, slot: int, act: _Active, idx: int,
                       final: bool):
        """Runs on the enqueue executor: park the slot's feed row
        (speculative decode-wave writes for a mid-prefill slot must
        drop — the sentinel is out of every table's range), dispatch
        one chunk forward through the slot's block-table row, and on
        the final chunk scatter the sampled first token into the
        device feed arrays — the very next wave decodes this slot
        without any host round trip."""
        # The admission plan that produced this chunk (or a concurrent
        # one) may have evicted spill-pending blocks this chunk's
        # writes will rewrite: gather first.
        self._drain_spills()
        jnp = self._jnp
        req = act.req
        C = self.prefill_chunk_tokens
        n = int(req.prompt_ids.size)
        start = idx * C
        end = min(start + C, n)
        width = end - start
        # Roofline accounting: this chunk's queries attend the whole
        # resident prefix (positions start..end-1 attend up to their
        # own index) — the same triangular term the monolithic path
        # accrues, sliced per chunk.
        self._prefill_flops += (
            self._flops_matmul_per_token * width
            + self._attn_flops_coeff * width * (start + end) / 2.0)
        ids = np.zeros((1, C), np.int32)
        ids[0, :width] = req.prompt_ids[start:end]
        # Padding queries of a partial final chunk park on the same
        # out-of-range sentinel: their cache writes drop and their
        # logits are never read (last_idx points at the last REAL
        # token).
        qpos = np.full((1, C), self.max_seq, np.int32)
        qpos[0, :width] = np.arange(start, end, dtype=np.int32)
        self._feed_tokens, self._feed_positions = self._feed_update(
            self._feed_tokens, self._feed_positions,
            jnp.asarray(np.asarray([slot], np.int32)),
            jnp.zeros((1,), jnp.int32),
            jnp.full((1,), self.max_seq, jnp.int32))
        # Slice the table row to the blocks chunks 0..idx cover: the
        # chunk's per-query-causal attention never reads past its own
        # end, and gathering the full max_seq-wide row would make
        # chunk 0 of a 4k prompt do 8x the key work it needs (summed
        # over chunks, ~2x the monolithic prefill's attention FLOPs —
        # eroding the stall win chunking buys).  One compiled program
        # per chunk INDEX (shape (idx+1)*bpc), all of them warmed by
        # the first full-length cold prefill; padding queries still
        # drop via the block_idx >= mb guard in paged_write.
        bpc = C // self.block_size
        nb = min((idx + 1) * bpc, self._tables.shape[1])
        self._note_program("chunk", nb)
        with self._block_lock:
            row = self._tables[slot:slot + 1, :nb].copy()
        (first, self._caches, chosen_lp, top_ids, top_lps) = \
            self._chunk_prefill(
                self.variables, self._caches, jnp.asarray(row),
                jnp.asarray(ids), jnp.asarray(qpos),
                jnp.asarray(np.asarray([max(width - 1, 0)], np.int32)),
                jnp.asarray(np.asarray([req.temperature], np.float32)),
                jnp.asarray(np.asarray([req.top_k], np.int32)),
                jnp.asarray(np.asarray([req.top_p], np.float32)),
                jnp.asarray(np.asarray([req.seed], np.int32)),
                jnp.asarray(np.asarray([n], np.int32)))
        if final:
            self._feed_tokens, self._feed_positions = \
                self._feed_update(
                    self._feed_tokens, self._feed_positions,
                    jnp.asarray(np.asarray([slot], np.int32)), first,
                    jnp.asarray(np.asarray([n], np.int32)))
        lp_h = ((chosen_lp, top_ids, top_lps)
                if req.logprobs > 0 else None)
        return first, lp_h

    async def _run_inner(self):
        loop = asyncio.get_event_loop()
        # The in-flight pipeline: decode waves AND prefill batches
        # share one FIFO of dispatched-but-unfetched device work.
        # Prefill rides it like any wave — admission enqueues prompt
        # forward + cache insert + feed scatter and returns WITHOUT a
        # host sync (the old blocking admission added a full
        # prefill-dispatch of inter-token stall to every live stream).
        # Items: ("decode", fetch_future, snapshot, t0) or
        # ("prefill", fetch_future, entries, t0) where entries is
        # [(slot, _Active|None)] in batch order.  Fetch futures are
        # submitted EAGERLY at enqueue (round trips overlap on the
        # 2-worker fetch executor); awaiting in FIFO order preserves
        # delivery order.
        inflight: deque = deque()
        try:
            # KFS_SANITIZE=1: jax.transfer_guard("disallow") armed on
            # this (the scheduler's) thread for the pipeline's whole
            # life — any implicit host<->device transfer inside the
            # decode loop raises, is counted as a forbidden_transfer
            # violation, and fails generation loudly.  The sanctioned
            # fetch/enqueue paths run on executor threads the guard
            # (thread-local) never covers, and additionally wrap
            # themselves in sanitizer.sanctioned_fetch().  Disabled,
            # loop_guard is one env read.
            with sanitizer.loop_guard(self.name):
                await self._run_pipeline(loop, inflight)
        finally:
            # A global failure (or close) can leave eagerly-submitted
            # fetch futures behind; consume their exceptions so a
            # poisoned chain doesn't log 'Future exception was never
            # retrieved' for every orphaned wave.
            for item in inflight:
                item[1].add_done_callback(
                    lambda f: f.cancelled() or f.exception())

    def _record_finish_span(self, req, tokens: int,
                            finished: str) -> None:
        """One completed `generator.generate` span per finished
        generation — EVERY terminal path records it (eos/length AND
        deadline timeouts), because the timed-out request is exactly
        the one the flight recorder pins and must find decode-phase
        evidence for."""
        if req.trace_id is None:
            return
        from kfserving_tpu.tracing import Span, tracer

        duration_s = max(0.0, time.perf_counter() - req.submit_t)
        tracer.record(Span(
            req.trace_id, "generator.generate",
            time.time() - duration_s, duration_s * 1000.0,
            {"tokens": tokens, "finish_reason": finished}))

    def _finalize_cost(self, req: _Request, finished: str) -> None:
        """Fold the request's accumulated accounting into ONE cost
        record (observability/attribution.py): attributed device ms by
        phase, prefill/decode tokens, peak blocks held, cache-saved
        tokens.  Every terminal path calls this — eos/length AND
        timeout/cancel, because the timed-out request is exactly the
        one the flight recorder pins and must find cost evidence
        for."""
        device_ms = {
            "prefill": round(req.prefill_device_ms, 3),
            "decode": round(req.decode_device_ms, 3),
        }
        if self.spec_tokens:
            # Draft/verify REFINE the decode figure (same busy
            # intervals, finer phase) — consumers summing
            # prefill+decode across requests still reconcile against
            # engine device time.
            device_ms["spec_draft"] = round(req.spec_draft_ms, 3)
            device_ms["spec_verify"] = round(req.spec_verify_ms, 3)
        attribution.observe(self.name, req.trace_id, {
            "trace_id": req.trace_id,
            "finish_reason": finished,
            "device_ms": device_ms,
            "prefill_tokens": int(req.prompt_ids.size),
            "decode_tokens": req.tokens_out,
            "blocks_held": req.blocks_held,
            "cache_hit_blocks": req.cache_hit_blocks,
            "cache_saved_tokens": req.cache_saved_tokens,
            "host_tier_hit_blocks": req.host_tier_hit_blocks,
            "host_tier_saved_tokens": req.host_tier_saved_tokens,
        })

    def _expire_deadlines(self) -> None:
        """Between decode waves: requests whose budget ran out get a
        terminal "timeout" event and free their slot (active) or leave
        the queue (pending) — the engine never spends another wave on
        a request nobody is still waiting for."""
        for i, s in enumerate(self._slots):
            if s is not None and s.req.deadline is not None \
                    and s.req.deadline.expired:
                s.req.out.put_nowait((None, "timeout"))
                self._record_finish_span(s.req, s.generated, "timeout")
                self._finalize_cost(s.req, "timeout")
                self._free_slot_state(i)
                self.requests_finished += 1
        if any(r.deadline is not None and r.deadline.expired
               for r in self._pending):
            keep = deque()
            while self._pending:
                r = self._pending.popleft()
                if r.deadline is not None and r.deadline.expired:
                    r.out.put_nowait((None, "timeout"))
                    self._record_finish_span(r, 0, "timeout")
                    self._finalize_cost(r, "timeout")
                    self.requests_finished += 1
                else:
                    keep.append(r)
            self._pending = keep

    async def _run_pipeline(self, loop, inflight: deque):
        while not self._closed:
            self._expire_deadlines()
            admitted = False
            while (not self._growth_starved and self._pending
                   and self._free_slot() is not None):
                force_miss = (self.block_size is not None
                              and await self._probe_prefix_fault())
                if self._is_cold(self._pending[0]):
                    # Cold long prompt: chunked admission — one slot,
                    # block-aligned chunks interleaving with decode
                    # waves (strict FIFO preserved: a cold request at
                    # the front is admitted, or blocks the queue on
                    # pool pressure exactly like a group plan would).
                    if not await self._admit_chunked(
                            loop, inflight, force_miss=force_miss):
                        break  # pool pressure: wait for frees
                    admitted = True
                    continue
                group, slots, bucket, dest_rows = \
                    self._take_prefill_group(force_miss=force_miss)
                if not group:
                    break  # paged pool pressure: wait for frees
                if (self.kv_tier is not None
                        and self._faultback_pending
                        and not await loop.run_in_executor(
                            self._enqueue_executor,
                            self._drain_faultbacks)):
                    # Transactional fault-back failure (the
                    # `engine.kv_faultback` chaos site, or entries
                    # evicted between probe and read): nothing was
                    # dispatched — roll the whole group's plans back
                    # and re-queue the requests at the front.  Their
                    # replans MISS the tier (the failed chains were
                    # dropped) and fall through to plain re-prefill.
                    for req, slot in zip(group, slots):
                        self._deregister_plan(slot)
                        self._schedule_block_release(slot)
                    for req in reversed(group):
                        self._pending.appendleft(req)
                    continue
                try:
                    firsts_h, lp_h = await loop.run_in_executor(
                        self._enqueue_executor,
                        self._enqueue_prefill_group,
                        group, slots, bucket, dest_rows)
                except Exception as e:
                    # An enqueue-time failure (e.g. OOM compiling a
                    # new bucket) fails THAT group; in-flight slots
                    # keep decoding.  Planned blocks release AND their
                    # provisional prefix registrations deregister —
                    # the blocks were never written, and leaking the
                    # refs/rows would shrink the pool while a stale
                    # chain entry could alias a later occupant's
                    # decode k/v (code-review r5).
                    logger.exception("prefill enqueue failed")
                    for req, slot in zip(group, slots):
                        req.out.put_nowait(
                            (None, f"error: prefill failed: {e}"))
                        self._deregister_plan(slot)
                        self._schedule_block_release(slot)
                    continue
                # Install slots NOW — the first tokens arrive at fetch
                # time, but the device feed arrays already carry them,
                # so the very next decode wave includes these slots.
                entries = []
                for req, slot in zip(group, slots):
                    # The prefill is enqueued: this slot's provisional
                    # prefix registrations are backed by dispatched
                    # writes (even for a cancelled row — its blocks
                    # get written and released, staying shareable).
                    self._confirm_plan(slot)
                    if req.cancelled:
                        # Cancelled between submit and here: deliver
                        # the terminal event (cancel() saw it neither
                        # pending nor active) and never occupy a slot.
                        # Planned blocks release (deferred — the just-
                        # enqueued prefill still writes them).
                        req.out.put_nowait((None, "cancelled"))
                        self._finalize_cost(req, "cancelled")
                        self.requests_finished += 1
                        self._schedule_block_release(slot)
                        entries.append((slot, None))
                        continue
                    act = _Active(req=req,
                                  length=req.prompt_ids.size,
                                  last_token=-1, generated=0)
                    self._slots[slot] = act
                    entries.append((slot, act))
                # Eager fetch: the D2H round trip starts NOW and
                # overlaps other fetches; the FIFO await below keeps
                # delivery order.
                fut = loop.run_in_executor(
                    self._executor, self._fetch_wave, firsts_h, lp_h)
                inflight.append(("prefill", fut, entries,
                                 time.perf_counter()))
                admitted = True
            active = any(s is not None for s in self._slots)
            if not active and not inflight:
                # No zombie dispatches can exist with an empty
                # pipeline: release everything deferred now (otherwise
                # a fully-idle engine would strand blocks until the
                # next wave advanced the counter).
                self._process_deferred_frees(force=True)
                # The HOLD's reason is gone with the pipeline empty
                # and the deferred frees landed; left set, it would
                # gate admissions while this branch `continue`s above
                # the only other reset — an await-free spin that
                # starves the event loop with the preempted request
                # parked in pending forever.
                self._set_hold(False)
                if not self._pending:
                    self._wakeup.clear()
                    if admitted:
                        continue
                    try:
                        await asyncio.wait_for(self._wakeup.wait(),
                                               timeout=1.0)
                    except asyncio.TimeoutError:
                        if not self._pending and not any(
                                s is not None for s in self._slots):
                            return  # idle: let the loop die; resubmit restarts
                continue
            # Paged mode: every active slot's table must cover the
            # positions the next pipeline_depth waves can reach.  A
            # slot the pool cannot grow is PREEMPTED, not failed: its
            # request re-queues with prompt = original + generated so
            # far (budget already consumed subtracted) and resumes
            # when blocks free — and because sampling noise is keyed
            # on (seed, absolute position), the resumed stream
            # produces EXACTLY the tokens the uninterrupted one would
            # have.  Only a request that could never fit again
            # (merged sequence exceeds the largest prefill bucket or
            # the whole pool) fails.
            # Mid-prefill slots: dispatch their next chunk into the
            # FIFO.  With live decode streams, ONE chunk in flight per
            # slot — the loop pops one FIFO item per iteration, so
            # chunks and waves alternate and a stream's stall is one
            # chunk's device time, not the whole prompt's.  With no
            # decodable streams there is nobody to stall: keep
            # pipeline_depth chunks in flight so the fetch RTT hides
            # behind the next chunk's compute.  This runs BEFORE the
            # growth pass: a slot whose FINAL chunk lands here becomes
            # decodable, and its table must grow to the decode horizon
            # before this same iteration's wave top-up — a
            # block-aligned prompt's first decode write lands one
            # block past the plan, and a wave carrying the ungrown
            # table would drop it (a cache hole, not a crash).
            decodable_now = any(s is not None and not s.prefilling
                                for s in self._slots)
            chunk_limit = 1 if decodable_now else max(
                2, self.pipeline_depth)
            for slot_i, s in enumerate(list(self._slots)):
                if (s is None or not s.prefilling
                        or self._slots[slot_i] is not s):
                    continue
                while (s.prefilling and s.chunks_inflight < chunk_limit
                       and s.chunk_next < s.chunk_total
                       and self._slots[slot_i] is s):
                    await self._step_chunk(loop, inflight, slot_i, s)
            failed = self._ensure_block_capacity()
            held = False
            if failed:
                # Pool pressure: cold prompts MID-CHUNKED-PREFILL
                # yield their blocks before any live stream is
                # re-prefilled — a prefilling slot has produced
                # nothing yet, so its restart is free (nothing was
                # sampled; a later re-admission replays the same
                # chunks bit-exactly, prefix-skipping the ones whose
                # blocks were registered before preemption), and the
                # freed blocks go to streams that already hold
                # context.
                preempted_prefill = False
                for i, s in enumerate(self._slots):
                    if s is not None and s.prefilling:
                        self._free_slot_state(i)
                        self._pending.appendleft(s.req)
                        self.preemptions += 1
                        preempted_prefill = True
                        TIMELINE.record(
                            "host", "preempt",
                            trace_id=s.req.trace_id, slot=i,
                            attrs={"phase": "prefill"})
                if preempted_prefill or self._deferred_frees:
                    # Blocks are already on their way back (a yield
                    # above, or frees maturing through the zombie-
                    # deferral window): HOLD the failing streams — no
                    # admissions, no new waves — until they land,
                    # instead of preempting streams that hold context
                    # (preempting both sides just re-creates the same
                    # over-committed pool: the ping-pong livelock the
                    # first cut of this path had).
                    held = True
                    failed = []
            self._set_hold(held)
            for i in failed:
                s = self._slots[i]
                if s is None:
                    continue
                merged_len = int(s.req.prompt_ids.size) + len(s.tokens)
                blocks_needed = -(-merged_len // self.block_size)
                # A merged sequence past the largest prefill bucket
                # still resumes when the chunked path can carry it.
                fits = (merged_len <= self.prefill_buckets[-1]
                        or (self.prefill_chunk_tokens is not None
                            and merged_len > self.prefill_chunk_tokens))
                if (not fits or blocks_needed > self.num_blocks
                        or s.req.max_new_tokens - s.generated < 1):
                    s.req.out.put_nowait(
                        (None, "error: kv cache pool exhausted"))
                    self._free_slot_state(i)
                    continue
                s.req.prompt_ids = np.concatenate(
                    [s.req.prompt_ids,
                     np.asarray(s.tokens, np.int32)])
                s.req.max_new_tokens -= s.generated
                self._free_slot_state(i)
                # Front of the queue: a preempted stream resumes
                # before new arrivals take its blocks.
                self._pending.appendleft(s.req)
                self.preemptions += 1
                TIMELINE.record("host", "preempt",
                                trace_id=s.req.trace_id, slot=i,
                                attrs={"phase": "decode"})
            # Keep the device pipeline_depth decode waves deep: wave
            # N+1's feed tokens are wave N's device outputs — no host
            # round trip sits between waves, so the fetch of wave N
            # below overlaps wave N+1's execution.  Prefill/chunk
            # items don't count toward depth (they are admission work
            # riding the same FIFO).
            decodable = [] if held else [
                s for s in self._slots
                if s is not None and not s.prefilling]
            waves = sum(1 for it in inflight
                        if it[0] in ("decode", "spec"))
            if self.spec_tokens > 0 and decodable and waves == 0:
                # Speculative mode runs depth-1: spec waves are
                # host-fed (the proposer needs each slot's committed
                # history), so wave N+1 cannot chain off wave N's
                # device feed — it waits for N's verdicts.  The
                # throughput lever here is K+1 tokens per dispatch,
                # not dispatch overlap; the adaptive-depth governor
                # has nothing to govern at depth 1.
                await self._spec_or_fallback_wave(loop, inflight)
                waves = 1
            elif self.spec_tokens == 0:
                while decodable and waves < self.pipeline_depth:
                    if (self.adaptive_depth and waves >= 1 and all(
                            s.req.max_new_tokens - s.generated
                            <= waves * self.steps_per_call
                            for s in decodable)):
                        # Adaptive depth: every active stream finishes
                        # (by token budget) within the waves already
                        # in flight — a speculative wave here could
                        # only decode garbage (the fixed-depth-2
                        # failure mode: ~45% wasted dispatches when
                        # finishes cluster, r5 A/B depth_speedup
                        # 0.98).  Staggered traffic keeps remaining
                        # work past the horizon and still gets the
                        # full configured depth.
                        self.suppressed_waves += 1
                        obs.generator_suppressed_waves_total().inc()
                        TIMELINE.record("host", "wave.suppressed")
                        break
                    kind_, toks_h, lp_h, snap, t0_ = \
                        await loop.run_in_executor(
                            self._enqueue_executor, self._enqueue_wave)
                    fut = loop.run_in_executor(
                        self._executor, self._fetch_wave, toks_h, lp_h)
                    inflight.append((kind_, fut, snap, t0_))
                    waves += 1
            if decodable and waves != self._depth_effective:
                self._depth_effective = waves
                obs.generator_pipeline_depth().set(waves)
            if not inflight:
                # Growth-starved drain reached an empty pipeline: no
                # zombie dispatch can exist, so the yielded blocks are
                # safe to release NOW — the held streams' growth retry
                # succeeds next iteration.
                self._process_deferred_frees(force=True)
                continue
            kind, fut, meta, t0 = inflight.popleft()
            t_await = time.perf_counter()
            try:
                fetched, lp, _worker_span = await fut
                # Host-blocked time is the LOOP-side await, not the
                # worker's span: eager fetches overlap on the worker
                # pool and their spans cover whole-wave latency — the
                # sum would exceed wall clock and lie in A/Bs.
                wait_s = time.perf_counter() - t_await
            except Exception as e:
                if kind == "prefill":
                    # Fail THAT group; in-flight slots keep decoding.
                    # (If the poisoned cache chain breaks later waves,
                    # their fetch error still fails everything.)
                    logger.exception("prefill failed")
                    for slot, act in meta:
                        if act is not None and \
                                self._slots[slot] is act:
                            self._free_slot_state(slot)
                            act.req.out.put_nowait(
                                (None, f"error: prefill failed: {e}"))
                    continue
                if kind == "chunk":
                    slot, act, _idx, _final = meta
                    act.chunks_inflight -= 1
                    logger.exception("chunk prefill failed")
                    if self._slots[slot] is act:
                        self._free_slot_state(slot)
                        act.req.out.put_nowait(
                            (None, f"error: prefill failed: {e}"))
                    continue
                raise
            # Union of busy intervals, NOT per-item spans: at depth>=2
            # the spans of consecutive items overlap, and summing them
            # would exceed wall clock (making depth A/Bs lie).
            now = time.perf_counter()
            busy = now - max(t0, self._last_fetch_done)
            self._last_fetch_done = now
            # Device-path timeline: one device-track slice per fetched
            # dispatch (the dispatch->fetch busy interval — the same
            # overlap-corrected span the device_s stats accumulate, so
            # the Perfetto view and the committed stats agree), plus
            # per-slot slices carrying each stream's trace id and a
            # pool-occupancy counter sample.
            wall = time.time()
            dev_dur = max(0.0, busy)
            if kind == "spec":
                self._decode_device_s += busy
                self._decode_wait_s += wait_s
                samples, draft, draft_ready_s = fetched
                entries, host_draft_ms = meta
                if self._spec_draft_fn is not None:
                    # The draft program completes before verify in
                    # device order (verify consumes its output), so
                    # the draft handle's ready time splits the busy
                    # interval into draft / verify device slices.
                    draft_ms = min(max(draft_ready_s, 0.0),
                                   dev_dur) * 1000.0
                    TIMELINE.record(
                        "device", "spec.draft",
                        dur_s=draft_ms / 1000.0, t_end=wall,
                        attrs={"k": self.spec_tokens})
                else:
                    # n-gram proposals are host work measured at
                    # proposal time; the whole device interval is
                    # verify.
                    draft_ms = host_draft_ms
                    TIMELINE.record(
                        "host", "spec.draft",
                        dur_s=draft_ms / 1000.0, t_end=wall,
                        attrs={"k": self.spec_tokens})
                verify_ms = dev_dur * 1000.0
                if self._spec_draft_fn is not None:
                    verify_ms = max(0.0, verify_ms - draft_ms)
                TIMELINE.record(
                    "device", "spec.verify",
                    dur_s=verify_ms / 1000.0, t_end=wall,
                    attrs={"k": self.spec_tokens,
                           "rows": len(entries),
                           "wait_ms": round(wait_s * 1000.0, 3)})
                for slot_i, s in entries:
                    if self._slots[slot_i] is s:
                        TIMELINE.record("slot", "spec.decode",
                                        dur_s=dev_dur, t_end=wall,
                                        trace_id=s.req.trace_id,
                                        slot=slot_i)
                self._record_pool_sample()
                self._distribute_spec(samples, draft, lp, entries,
                                      device_ms=dev_dur * 1000.0,
                                      draft_ms=draft_ms,
                                      verify_ms=verify_ms)
            elif kind == "decode":
                self._decode_device_s += busy
                self._decode_wait_s += wait_s
                TIMELINE.record(
                    "device", "decode.wave", dur_s=dev_dur, t_end=wall,
                    attrs={"steps": self.steps_per_call,
                           "wait_ms": round(wait_s * 1000.0, 3)})
                for slot_i, s in enumerate(meta):
                    if s is not None and self._slots[slot_i] is s:
                        TIMELINE.record("slot", "decode",
                                        dur_s=dev_dur, t_end=wall,
                                        trace_id=s.req.trace_id,
                                        slot=slot_i)
                self._record_pool_sample()
                self._distribute(fetched, lp, meta,
                                 device_ms=dev_dur * 1000.0)
            elif kind == "chunk":
                self._prefill_device_s += busy
                self._prefill_wait_s += wait_s
                # The stall THIS chunk inserted between decode
                # fetches — the per-chunk slice of what a monolithic
                # prefill would have injected all at once.
                obs.generator_prefill_chunk_stall_ms().observe(
                    busy * 1000.0)
                slot, act, _idx, final = meta
                TIMELINE.record(
                    "device", "prefill.chunk", dur_s=dev_dur,
                    t_end=wall, trace_id=act.req.trace_id, slot=slot,
                    attrs={"chunk": _idx, "final": final})
                TIMELINE.record("slot", "prefill.chunk",
                                dur_s=dev_dur, t_end=wall,
                                trace_id=act.req.trace_id, slot=slot,
                                attrs={"chunk": _idx})
                act.chunks_inflight -= 1
                # A chunk dispatch serves exactly one request: its
                # whole busy interval is that request's prefill cost.
                act.req.prefill_device_ms += dev_dur * 1000.0
                if final and self._slots[slot] is act:
                    # The final chunk carries the stream's first
                    # sampled token (the feed arrays got it at enqueue
                    # — intervening waves already decoded this slot;
                    # FIFO order delivers this token before theirs).
                    self.prefill_requests += 1
                    rec = None
                    n_lp = act.req.logprobs
                    if lp is not None and n_lp > 0:
                        rec = (float(lp[0][0]),
                               [(int(t), float(p)) for t, p in
                                zip(lp[1][0][:n_lp], lp[2][0][:n_lp])])
                    self._emit(slot, int(fetched[0]), rec)
            else:
                self._prefill_device_s += busy
                self._prefill_wait_s += wait_s
                TIMELINE.record(
                    "device", "prefill.bucket", dur_s=dev_dur,
                    t_end=wall, attrs={"batch": len(meta)})
                for slot_i, act in meta:
                    if act is not None and self._slots[slot_i] is act:
                        TIMELINE.record("slot", "prefill",
                                        dur_s=dev_dur, t_end=wall,
                                        trace_id=act.req.trace_id,
                                        slot=slot_i)
                self._finish_prefill(fetched, lp, meta,
                                     device_ms=dev_dur * 1000.0)
            self._process_deferred_frees()

    def _finish_prefill(self, firsts: np.ndarray, lp, entries,
                        device_ms: float = 0.0):
        """Deliver a fetched prefill batch's first tokens.  A slot
        whose _Active was replaced since enqueue (cancel) discards its
        row, exactly like _distribute."""
        self.prefills += 1
        # Even split of the bucket dispatch across the rows whose cost
        # records are still OPEN (slot unchanged since enqueue).  A
        # cancelled row's record was finalized at cancel time —
        # mutating it would be lost work — so its computed prompt's
        # share redistributes onto the survivors of the same dispatch:
        # device time stays conserved across stored records.
        live = [act for slot, act in entries
                if act is not None and self._slots[slot] is act]
        share_ms = device_ms / len(live) if live else 0.0
        for act in live:
            act.req.prefill_device_ms += share_ms
        for i, (slot, act) in enumerate(entries):
            if act is None or self._slots[slot] is not act:
                continue
            self.prefill_requests += 1
            rec = None
            n_lp = act.req.logprobs
            if lp is not None and n_lp > 0:
                rec = (float(lp[0][i]),
                       [(int(t), float(p)) for t, p in
                        zip(lp[1][i][:n_lp], lp[2][i][:n_lp])])
            self._emit(slot, int(firsts[i]), rec)

    def _note_program(self, kind: str, *signature) -> None:
        """Record one dispatched program shape (enqueue-executor
        thread only).  The first sighting per (kind, signature) flows
        to compile_cache.note_compilation — post-warmup sightings are
        KFS_SANITIZE recompile violations; off, this is a set probe."""
        key = (kind,) + signature
        if key not in self._dispatched_programs:
            self._dispatched_programs.add(key)
            compile_cache.note_compilation(self.sanitize_source, key)

    def _enqueue_wave(self):
        """Dispatch one K-step decode wave (non-blocking: JAX async
        dispatch).  Consumes the device-resident caches + feed arrays
        and replaces them with the wave's output handles."""
        jnp = self._jnp
        # Slot growth for this wave may have evicted spill-pending
        # blocks the wave's decode writes will rewrite: gather first.
        self._drain_spills()
        self._note_program("decode", self.max_slots,
                           self.steps_per_call)
        temps, top_ks, top_ps, seeds, want_lp = self._sampling_arrays()
        (toks, self._caches, self._feed_tokens, self._feed_positions,
         chosen_lp, top_ids, top_lps) = self._decode(
            self.variables, self._caches, self._table_device(),
            self._feed_tokens, self._feed_positions,
            jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), jnp.asarray(seeds))
        lp_h = (chosen_lp, top_ids, top_lps) if want_lp else None
        self.decode_steps += 1
        # Snapshot records mid-chunked-prefill slots as None: this
        # wave reads their PARKED feed row (out-of-range sentinel —
        # writes drop, tokens are garbage by design).  The flag on the
        # live _Active can flip to decodable before this wave's fetch
        # lands, so the decision must be frozen at enqueue.
        snapshot = [None if (s is not None and s.prefilling) else s
                    for s in self._slots]
        return ("decode", toks, lp_h, snapshot,
                time.perf_counter())

    def _fetch_wave(self, toks_h, lp_h):
        """Runs on the executor thread: the D2H fetch that joins the
        device timeline (block_until_ready on this transport acks the
        dispatch without joining — only the fetch truly waits).
        Returns (tokens, lp, wait_s); the caller attributes the wait
        to decode or prefill (this path serves both kinds)."""
        t0 = time.perf_counter()
        # THE sanctioned generation fetch: the one place device
        # handles become host arrays, on the fetch executor.
        with sanitizer.sanctioned_fetch():
            # kfslint: disable=host-sync — sanctioned fetch site: the
            # wave's D2H join, off-loop on the fetch executor.
            tokens = np.asarray(toks_h)
            lp = None
            if lp_h is not None:
                # kfslint: disable=host-sync — sanctioned fetch site:
                # logprob handles fetched beside their wave's tokens.
                lp = tuple(np.asarray(h) for h in lp_h)
        return tokens, lp, time.perf_counter() - t0

    def _enqueue_prefill_group(self, group: List[_Request],
                               slots: List[int],
                               bucket: int,
                               dest_rows: Optional[List[List[int]]]
                               = None):
        """Runs on the enqueue executor: dispatch one bucket-padded
        prefill for the WHOLE group (a burst of arrivals rides one
        dispatch), chain the cache insert and the device-feed scatter
        off it, and return the first-token handles WITHOUT any host
        sync — prompt ingestion rides the same in-flight pipeline as
        decode waves, so admissions no longer stall live streams by a
        full prefill dispatch.  The batch pads to a pow2 row bucket so
        compile count stays bounded; padding rows carry an
        out-of-bounds slot sentinel the scatters drop."""
        # This group's plans may have evicted spill-pending blocks the
        # insert below will rewrite: gather first.
        self._drain_spills()
        jnp = self._jnp
        b = len(group)
        b_bucket = 1
        while b_bucket < b:
            b_bucket *= 2
        ids = np.zeros((b_bucket, bucket), np.int32)
        lengths = np.ones(b_bucket, np.int32)  # dummy rows: length 1
        temps = np.zeros(b_bucket, np.float32)
        top_ks = np.zeros(b_bucket, np.int32)
        top_ps = np.ones(b_bucket, np.float32)
        seeds = np.zeros(b_bucket, np.int32)
        slot_arr = np.full(b_bucket, self.max_slots, np.int32)  # OOB
        want_lp = False
        for i, (req, slot) in enumerate(zip(group, slots)):
            n = req.prompt_ids.size
            ids[i, :n] = req.prompt_ids
            lengths[i] = n
            temps[i] = req.temperature
            top_ks[i] = req.top_k
            top_ps[i] = req.top_p
            seeds[i] = req.seed
            slot_arr[i] = slot
            want_lp = want_lp or req.logprobs > 0
        # Roofline accounting: real-token FLOPs (2P matmul + causal
        # attention's triangular sum) and the bucket's token padding —
        # padded rows/positions burn device time without FLOPs that
        # count, which is exactly what the padding-waste gauge shows.
        for req in group:
            n = int(req.prompt_ids.size)
            self._prefill_flops += (
                self._flops_matmul_per_token * n
                + self._attn_flops_coeff * n * (n + 1) / 2.0)
        rec = self._prefill_bucket_tokens.setdefault(bucket,
                                                     [0.0, 0.0])
        rec[0] += sum(int(r.prompt_ids.size) for r in group)
        rec[1] += b_bucket * bucket
        self._note_program("prefill", b_bucket, bucket)
        firsts, new_caches, chosen_lp, top_ids, top_lps = \
            self._prefill(
                self.variables, jnp.asarray(ids), jnp.asarray(lengths),
                jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), jnp.asarray(seeds))
        if dest_rows is not None:
            # Paged: per-chunk destination blocks (-1 = shared prefix
            # hit or padding row — the scatter drops those chunks).
            chunks = bucket // self.block_size
            dest = np.full((b_bucket, chunks), -1, np.int32)
            for i, row in enumerate(dest_rows):
                dest[i, :len(row)] = row
            insert_arg = jnp.asarray(dest)
        else:
            insert_arg = jnp.asarray(slot_arr)
        self._caches = self._insert(self._caches, new_caches,
                                    insert_arg)
        # The admitted slots' first feed token/position land in the
        # device-resident feed arrays; rows of slots NOT in this group
        # keep their device values (the last enqueued wave's outputs,
        # which the host may not have seen yet).  The next decode wave
        # therefore includes these slots before the host ever sees
        # their first token.
        self._feed_tokens, self._feed_positions = self._feed_update(
            self._feed_tokens, self._feed_positions,
            jnp.asarray(slot_arr), firsts,
            jnp.asarray(lengths))
        lp_h = (chosen_lp, top_ids, top_lps) if want_lp else None
        return firsts, lp_h

    def _sampling_arrays(self):
        """Per-slot sampling parameter arrays for a decode dispatch.
        Feed tokens/positions live on device (the previous wave's
        outputs); only the sampling knobs come from host state."""
        S = self.max_slots
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.ones(S, np.float32)
        seeds = np.zeros(S, np.int32)
        want_lp = False
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            temps[i] = s.req.temperature
            top_ks[i] = s.req.top_k
            top_ps[i] = s.req.top_p
            seeds[i] = s.req.seed
            want_lp = want_lp or s.req.logprobs > 0
        return temps, top_ks, top_ps, seeds, want_lp

    def _emit(self, slot: int, token: int, lp_rec=None):
        """Account a newly produced token for `slot` and deliver it (or
        the finish marker) to the request's stream.

        Invariant: `length` counts tokens whose k/v are IN the cache;
        `last_token` is the token the next decode step feeds at
        position `length`.  The produced token's k/v are NOT in the
        cache yet — the step that consumes it writes them (so this
        method never touches `length`)."""
        s = self._slots[slot]
        s.generated += 1
        s.req.tokens_out += 1
        self.tokens_generated += 1
        obs.llm_tokens_total().labels(direction="out").inc()
        # Generation latency series: first emission is TTFT, later
        # ones inter-token gaps; both carry the request's trace id as
        # an exemplar so a slow tail links straight to its trace.
        now = time.perf_counter()
        if s.req.last_emit_t is None:
            obs.llm_ttft_ms().observe(
                (now - s.req.submit_t) * 1000.0,
                trace_id=s.req.trace_id)
        else:
            obs.llm_inter_token_ms().observe(
                (now - s.req.last_emit_t) * 1000.0,
                trace_id=s.req.trace_id)
        s.req.last_emit_t = now
        finished = None
        if self.eos_id is not None and token == self.eos_id:
            finished = "eos"
        elif s.generated >= s.req.max_new_tokens:
            finished = "length"
        if finished == "eos":
            # EOS is a stop signal, not content.
            s.req.out.put_nowait((None, "eos"))
        else:
            if lp_rec is not None:
                # Records align 1:1 with CONTENT tokens (an EOS stop
                # delivers no token, so it records no logprob).
                s.req.lp_chosen.append(lp_rec[0])
                s.req.lp_top.append(lp_rec[1])
            s.tokens.append(token)
            s.req.out.put_nowait((token, finished))
        if finished is not None:
            duration_s = now - s.req.submit_t
            if duration_s > 0:
                obs.llm_tokens_per_second().observe(
                    s.generated / duration_s,
                    trace_id=s.req.trace_id)
            self._record_finish_span(s.req, s.generated, finished)
            self._finalize_cost(s.req, finished)
            self._free_slot_state(slot)
            self.requests_finished += 1
        else:
            s.last_token = token

    def _distribute(self, tokens: np.ndarray, lp, snapshot,
                    device_ms: float = 0.0):
        """tokens [S, K]: deliver each slot's chunk in order.  A slot
        only consumes its row if the SAME _Active object that was
        in the slot at enqueue time is still there — a slot freed (or
        freed-and-readmitted) between enqueue and fetch was decoding
        garbage for this wave, and its row is discarded (that waste is
        the pipelining trade; counted in wasted_token_steps).  A slot
        finishing mid-chunk discards its remaining positions — at most
        K-1 steps of waste."""
        k = tokens.shape[1]
        self._token_steps += k
        resident_tokens = 0
        # Even split of the wave's busy interval across the live
        # streams it decoded: the per-request decode cost sums to the
        # engine's device time (additive attribution), and garbage
        # rows (freed slots) are excluded — their waste already shows
        # in goodput_ratio.
        live = sum(1 for i, s in enumerate(snapshot)
                   if s is not None and self._slots[i] is s)
        share_ms = device_ms / live if live else 0.0
        for i, s in enumerate(snapshot):
            if s is None:
                continue
            if self._slots[i] is not s:
                # Freed (EOS/budget/cancel) after this wave was
                # enqueued: the device decoded K garbage steps for it.
                self._wasted_token_steps += k
                continue
            self._occupied_slot_steps += k
            s.req.decode_device_ms += share_ms
            if self.block_size is not None:
                s.req.blocks_held = max(
                    s.req.blocks_held,
                    -(-int(s.length) // self.block_size))
            # Roofline accounting over LIVE rows: matmul FLOPs per fed
            # token plus attention over the slot's resident context
            # (length at wave start — within a K-step wave the drift
            # is < K positions, noise against the ±10% stats bar).
            self._decode_flops += k * (self._flops_matmul_per_token
                                       + self._attn_flops_coeff
                                       * s.length)
            resident_tokens += s.length
            n_lp = s.req.logprobs
            for j in range(k):
                if self._slots[i] is not s:
                    # Finished mid-chunk: remaining positions wasted.
                    self._wasted_token_steps += k - j
                    break
                # Each scanned step wrote the fed token's k/v at the
                # slot's position: the cache grew by one per step.
                s.length += 1
                rec = None
                if lp is not None and n_lp > 0:
                    rec = (float(lp[0][i, j]),
                           [(int(t), float(p)) for t, p in
                            zip(lp[1][i, j][:n_lp],
                                lp[2][i, j][:n_lp])])
                self._emit(i, int(tokens[i, j]), rec)
        if resident_tokens:
            # Decode reads every live slot's resident KV plus the full
            # parameter set once per token step — the bandwidth-bound
            # working set the HBM-utilization gauge divides by peak.
            self._decode_hbm_bytes += k * (
                self._param_read_bytes
                + resident_tokens * self._kv_bytes_per_token)

    # -- speculative decoding ----------------------------------------------
    async def _spec_or_fallback_wave(self, loop, inflight) -> None:
        """Enqueue exactly one wave in speculative mode: a draft/verify
        spec wave over the host-feedable slots, or a plain resynced
        decode wave when chaos trips a spec fault site or no slot has
        a host-visible last token yet (a monolithic prefill's first
        token can still be in the FIFO — its device feed row is
        correct, so the plain wave decodes it; the slot joins spec
        waves once the fetch lands).  Either way the OUTPUT tokens are
        bit-identical to non-speculative decode — only the dispatch
        shape differs."""
        eligible = [(i, s) for i, s in enumerate(self._slots)
                    if s is not None and not s.prefilling
                    and s.last_token >= 0]
        fall_site = await self._probe_spec_fault() if eligible else None
        if eligible and fall_site is None:
            ngram = None
            windows = None
            host_ms = 0.0
            if self._spec_draft_fn is not None:
                windows = self._build_draft_windows(eligible)
            else:
                ngram, host_ms = self._propose_ngram(eligible)
            kind_, handles, lp_h, meta_, t0_ = \
                await loop.run_in_executor(
                    self._enqueue_executor, self._enqueue_spec_wave,
                    eligible, ngram, windows, host_ms)
            fut = loop.run_in_executor(
                self._executor, self._fetch_spec, handles, lp_h)
            inflight.append((kind_, fut, meta_, t0_))
            return
        if fall_site is not None:
            self._count_spec_fallback(fall_site)
        kind_, toks_h, lp_h, snap, t0_ = await loop.run_in_executor(
            self._enqueue_executor, self._enqueue_resynced_wave)
        fut = loop.run_in_executor(
            self._executor, self._fetch_wave, toks_h, lp_h)
        inflight.append((kind_, fut, snap, t0_))

    async def _probe_spec_fault(self) -> Optional[str]:
        """Chaos seams of the speculative path, probed ON the loop
        (async injected latency never blocks the scheduler).  An
        injected error on either seam degrades THIS wave to plain
        non-speculative decode — same tokens, fewer per dispatch.
        configured() keeps the no-faults hot path at two dict
        lookups."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import (
            FaultInjected,
            faults,
        )

        if faults.configured(fault_sites.ENGINE_SPEC_DRAFT):
            try:
                await faults.inject(fault_sites.ENGINE_SPEC_DRAFT,
                                    key=self.name)
            except FaultInjected:
                return "draft"
        if faults.configured(fault_sites.ENGINE_SPEC_VERIFY):
            try:
                await faults.inject(fault_sites.ENGINE_SPEC_VERIFY,
                                    key=self.name)
            except FaultInjected:
                return "verify"
        return None

    def _count_spec_fallback(self, site: str) -> None:
        self.spec_fallbacks[site] = \
            self.spec_fallbacks.get(site, 0) + 1
        obs.specdec_fallbacks_total().labels(
            model=self.name, site=site).inc()

    def _spec_history(self, s: _Active) -> List[int]:
        """A slot's committed token stream: prompt + emitted content
        tokens (s.tokens ends with last_token — the _emit invariant),
        which is exactly the prefix the next sampled token extends."""
        return list(s.req.prompt_ids) + s.tokens

    def _propose_ngram(self, eligible) -> Tuple[np.ndarray, float]:
        """Host-side prompt-lookup proposals for the eligible rows.
        Runs on the loop thread: pure numpy/list scanning, no device
        work — its cost is measured and reported as the n-gram arm's
        draft overhead."""
        t0 = time.perf_counter()
        draft = np.zeros((self.max_slots, self.spec_tokens), np.int32)
        for i, s in eligible:
            draft[i] = self._ngram.propose(self._spec_history(s))
        return draft, (time.perf_counter() - t0) * 1000.0

    def _build_draft_windows(self, eligible) -> np.ndarray:
        from kfserving_tpu.engine.speculative import rolling_windows

        return rolling_windows(
            [self._spec_history(s) for _i, s in eligible],
            self.max_slots, [i for i, _s in eligible],
            self._draft_window)

    def _enqueue_spec_wave(self, eligible, ngram, windows,
                           host_draft_ms):
        """Runs on the enqueue executor: dispatch the draft proposer
        (when a draft model is configured) and the K+1-position verify
        as ONE chained device program pair — the verify consumes the
        draft's output handle, so no host round trip separates them
        and the fetch below joins both.  Rows not in `eligible` park
        on the max_seq position sentinel: their writes drop (paged OOB
        sentinel / dense mode='drop') and their samples are
        discarded."""
        jnp = self._jnp
        self._drain_spills()
        S = self.max_slots
        K = self.spec_tokens
        last = np.zeros(S, np.int32)
        qpos = np.full((S, K + 1), self.max_seq, np.int32)
        for i, s in eligible:
            last[i] = s.last_token
            qpos[i] = s.length + np.arange(K + 1, dtype=np.int32)
        temps, top_ks, top_ps, seeds, want_lp = \
            self._sampling_arrays()
        if windows is not None:
            self._note_program("spec_draft", S, self._draft_window)
            draft_dev = self._spec_draft_fn(self._draft_variables,
                                            jnp.asarray(windows))
        else:
            draft_dev = jnp.asarray(ngram)
        self._note_program("spec_verify", S, K + 1)
        (samples, draft_echo, self._caches, chosen_lp, top_ids,
         top_lps) = self._spec_verify(
            self.variables, self._caches, self._table_device(),
            jnp.asarray(last), draft_dev, jnp.asarray(qpos),
            jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), jnp.asarray(seeds))
        self.decode_steps += 1
        self.spec_waves += 1
        lp_h = (chosen_lp, top_ids, top_lps) if want_lp else None
        return ("spec", (samples, draft_echo, windows is not None),
                lp_h, (list(eligible), host_draft_ms),
                time.perf_counter())

    def _fetch_spec(self, handles, lp_h):
        """Runs on the fetch executor: join the spec wave's device
        work.  The draft handle is readied FIRST — the verify program
        consumes the draft's output, so draft-ready time is the
        draft/verify split point of the wave's busy interval (zero
        extra transfers: block_until_ready moves no data)."""
        samples_h, draft_h, timed_draft = handles
        t0 = time.perf_counter()
        with sanitizer.sanctioned_fetch():
            draft_ready_s = 0.0
            if timed_draft:
                # kfslint: disable=host-sync — sanctioned fetch site:
                # readiness probe that splits draft vs verify device
                # time; the verify fetch below is the real join.
                draft_h.block_until_ready()
                draft_ready_s = time.perf_counter() - t0
            # kfslint: disable=host-sync — sanctioned fetch site: the
            # spec wave's D2H join (verdicts + echoed proposals in one
            # round trip).
            samples = np.asarray(samples_h)
            draft = np.asarray(draft_h)
            lp = None
            if lp_h is not None:
                # kfslint: disable=host-sync — sanctioned fetch site:
                # logprob handles fetched beside their wave's tokens.
                lp = tuple(np.asarray(h) for h in lp_h)
        return ((samples, draft, draft_ready_s), lp,
                time.perf_counter() - t0)

    def _enqueue_resynced_wave(self):
        """Runs on the enqueue executor: re-sync the device feed
        arrays from host slot state, then dispatch a plain decode
        wave.  Spec waves are host-fed and do NOT maintain the
        device-resident feed chain, so a fallback to the plain wave
        path must first restore each feedable row (rows whose first
        token is still in the FIFO — last_token < 0 — keep the values
        the prefill enqueue scattered, which are already correct;
        parked/free rows keep their harmless stale values)."""
        jnp = self._jnp
        S = self.max_slots
        slot_arr = np.full(S, self.max_slots, np.int32)  # OOB: keep
        toks = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        for i, s in enumerate(self._slots):
            if s is not None and not s.prefilling \
                    and s.last_token >= 0:
                slot_arr[i] = i
                toks[i] = s.last_token
                pos[i] = s.length
        self._note_program("feed_resync", S)
        self._feed_tokens, self._feed_positions = self._feed_update(
            self._feed_tokens, self._feed_positions,
            jnp.asarray(slot_arr), jnp.asarray(toks),
            jnp.asarray(pos))
        return self._enqueue_wave()

    def _distribute_spec(self, samples: np.ndarray,
                         draft: np.ndarray, lp, entries,
                         device_ms: float = 0.0,
                         draft_ms: float = 0.0,
                         verify_ms: float = 0.0):
        """samples/draft [S, K+1] / [S, K]: commit each live row's
        longest agreeing prefix.  Row acceptance a (1..K+1) counts the
        target's own draws that are safe to emit: draw j extends a
        prefix that is only correct if every earlier draft token
        matched, so emission stops at the first draft/target mismatch
        — the mismatching TARGET draw itself is still correct (it was
        sampled from the true prefix) and is emitted as position a-1.
        All-K agreement emits the K+1'th \"bonus\" draw the verify got
        for free.  No cache rollback: the host length pointer advances
        only over emitted positions, and later waves overwrite the
        rejected positions' k/v before any query can attend them."""
        K = self.spec_tokens
        kp1 = K + 1
        self._token_steps += kp1
        proposer = ("draft" if self._spec_draft_fn is not None
                    else "ngram")
        live = [(i, s) for i, s in entries if self._slots[i] is s]
        dead = len(entries) - len(live)
        if dead:
            # Freed (EOS/budget/cancel) after enqueue: the device
            # verified K+1 garbage positions for those rows.
            self._wasted_token_steps += dead * kp1
        share_ms = device_ms / len(live) if live else 0.0
        draft_share = draft_ms / len(live) if live else 0.0
        verify_share = verify_ms / len(live) if live else 0.0
        accepted_wave = 0
        resident_tokens = 0
        for i, s in live:
            a = 1
            while a <= K and int(draft[i, a - 1]) == \
                    int(samples[i, a - 1]):
                a += 1
            self.spec_proposed_tokens += K
            self.spec_accepted_tokens += a - 1
            accepted_wave += a - 1
            self._spec_lengths.append(a)
            obs.specdec_accepted_length_tokens().labels(
                model=self.name).observe(float(a))
            s.req.decode_device_ms += share_ms
            s.req.spec_draft_ms += draft_share
            s.req.spec_verify_ms += verify_share
            if self.block_size is not None:
                s.req.blocks_held = max(
                    s.req.blocks_held,
                    -(-int(s.length + a) // self.block_size))
            # Roofline over ACCEPTED tokens only: rejected positions
            # burn device time without useful FLOPs (that waste is the
            # acceptance-rate trade, visible in goodput_ratio).
            self._decode_flops += a * (self._flops_matmul_per_token
                                       + self._attn_flops_coeff
                                       * s.length)
            resident_tokens += s.length
            n_lp = s.req.logprobs
            emitted = 0
            for j in range(a):
                if self._slots[i] is not s:
                    # Finished (EOS/budget) mid-row: the rest of the
                    # agreeing prefix is past the stream's end.
                    break
                s.length += 1
                rec = None
                if lp is not None and n_lp > 0:
                    rec = (float(lp[0][i, j]),
                           [(int(t), float(p)) for t, p in
                            zip(lp[1][i, j][:n_lp],
                                lp[2][i, j][:n_lp])])
                self._emit(i, int(samples[i, j]), rec)
                emitted += 1
            self.spec_emitted_tokens += emitted
            self._occupied_slot_steps += emitted
            self._wasted_token_steps += kp1 - emitted
        if live:
            obs.specdec_proposed_tokens_total().labels(
                model=self.name, proposer=proposer).inc(len(live) * K)
            obs.specdec_accepted_tokens_total().labels(
                model=self.name, proposer=proposer).inc(accepted_wave)
            obs.specdec_draft_ms().labels(
                model=self.name, proposer=proposer).observe(draft_ms)
            if self.spec_proposed_tokens:
                obs.specdec_acceptance_ratio().labels(
                    model=self.name).set(
                        self.spec_accepted_tokens
                        / self.spec_proposed_tokens)
        self._spec_draft_s += draft_ms / 1000.0
        self._spec_verify_s += verify_ms / 1000.0
        if resident_tokens:
            # One parameter read serves all K+1 positions — the whole
            # point of speculation on a bandwidth-bound decode — while
            # each of the K+1 queries streams the resident KV.
            self._decode_hbm_bytes += (
                self._param_read_bytes
                + kp1 * resident_tokens * self._kv_bytes_per_token)

    def draft_param_bytes(self) -> int:
        """HBM ledger contribution of the configured draft model (0
        when speculation runs the n-gram head or is off)."""
        if self._draft_variables is None:
            return 0
        jax = self._jax
        return sum(int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
                   for x in jax.tree.leaves(self._draft_variables))


def _pow2_buckets(max_seq: int) -> List[int]:
    out, b = [], 16
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return out
