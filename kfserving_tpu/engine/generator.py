"""GenerationEngine: KV-cache incremental decoding with continuous
batching.

The reference has no generative serving at all — models are opaque
request/response artifacts (reference pkg/apis/serving/v1beta1/
predictor.go:33-59) and its batcher coalesces whole requests
(pkg/batcher/handler.go:129-150).  Token generation breaks that model:
one request is hundreds of sequential device steps, and throughput
comes from batching *steps across requests*, not requests.  This engine
is the TPU-first design for that:

- **slot caches, static shapes**: the KV cache is a fixed pool of
  `max_slots` sequence slots, per layer [S, max_seq, H, D].  The decode
  step is ONE jit-compiled program over all S slots, compiled once and
  reused for the life of the server — requests joining or leaving never
  change a shape, so XLA never recompiles (the continuous-batching
  analogue of the engine's batch buckets).
- **prefill/decode split**: prompt ingestion runs as a separate
  bucketed forward (suffix-padded, flash-eligible at long L, one
  compile per bucket) that returns the prompt's k/v for every layer;
  a jitted scatter inserts them into a free slot.  Decode then costs
  O(1) tokens per step.
- **continuous batching**: new requests are admitted at step
  boundaries — prefill, insert, then the request's slot joins the next
  decode step alongside in-flight sequences; finished slots free
  immediately (EOS or token budget).  The admission policy is
  prefill-priority: arrivals never wait for the current generation
  wave to drain (the "continuous" in continuous batching).
- **on-device sampling**: greedy and temperature (Gumbel trick) per
  slot; only the [S] int32 token vector crosses the host boundary per
  step — never the [S, V] logits (1.6 MB/step for a GPT-2 vocab; the
  host link is the serving bottleneck, ROOFLINE.md).
- **donated caches**: the decode step donates the cache buffers, so
  XLA updates them in place — HBM holds ONE cache pool, not
  step-transient copies.

Cache HBM is accounted via `cache_bytes()` so the predictor can admit
params + cache against engine/hbm.py's budget.
"""

import asyncio
import concurrent.futures
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import numpy as np

from kfserving_tpu.protocol.errors import InferenceError, InvalidInput

logger = logging.getLogger("kfserving_tpu.engine.generator")


@dataclass
class _Request:
    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float
    out: asyncio.Queue = field(default_factory=asyncio.Queue)
    cancelled: bool = False


@dataclass
class _Active:
    req: _Request
    length: int          # valid cache entries (prompt + generated so far)
    last_token: int      # token to feed at position `length`
    generated: int


class GenerationEngine:
    """Continuous-batching token generation over one device/mesh.

    module: a DecoderLM-contract Flax module (models/decoder.py): full
        forward with `return_cache=True` and decode with `kv_cache` +
        `positions`.
    variables: initialized/restored model variables.
    """

    def __init__(self, module, variables, *,
                 max_slots: int = 8,
                 max_seq: int = 512,
                 prefill_buckets: Optional[List[int]] = None,
                 eos_id: Optional[int] = None,
                 steps_per_call: int = 1,
                 rng_seed: int = 0,
                 mesh=None,
                 name: str = "decoder"):
        import jax
        import jax.numpy as jnp

        self._jax = jax
        self._jnp = jnp
        self.module = module
        self.variables = variables
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        if steps_per_call < 1:
            raise InvalidInput("steps_per_call must be >= 1")
        self.steps_per_call = int(steps_per_call)
        cfg = module.config
        if self.max_seq > cfg.max_seq:
            raise InvalidInput(
                f"engine max_seq {self.max_seq} exceeds the model's "
                f"position table {cfg.max_seq}")
        self.eos_id = eos_id
        self.name = name
        self.mesh = mesh
        buckets = sorted(set(prefill_buckets or
                             _pow2_buckets(self.max_seq)))
        if buckets[-1] > self.max_seq:
            raise InvalidInput(
                f"prefill bucket {buckets[-1]} exceeds max_seq "
                f"{self.max_seq}")
        self.prefill_buckets = buckets
        self._rng = jax.random.PRNGKey(rng_seed)
        self._step_counter = 0

        n_layers = cfg.num_layers
        cache_shape = (self.max_slots, self.max_seq, cfg.num_heads,
                       cfg.head_dim)
        cache_dtype = cfg.dtype
        self._cache_shape = cache_shape
        self._cache_dtype = cache_dtype
        self._caches = [
            (jnp.zeros(cache_shape, cache_dtype),
             jnp.zeros(cache_shape, cache_dtype))
            for _ in range(n_layers)
        ]
        if mesh is not None:
            # Tensor parallelism: the cache shards on the heads axis,
            # exactly like the q/k/v projections that fill it
            # (parallel/sharding.py transformer_rules) — cache writes
            # and decode attention stay device-local per head group;
            # the per-layer psum after the out-projection is the only
            # collective.  Callers pass variables already sharded.
            from jax.sharding import NamedSharding, PartitionSpec

            tp = mesh.shape.get("tp", 1)
            heads_axis = "tp" if cfg.num_heads % max(tp, 1) == 0 else None
            sharding = NamedSharding(
                mesh, PartitionSpec(None, None, heads_axis, None))
            self._caches = [
                (jax.device_put(k, sharding), jax.device_put(v, sharding))
                for k, v in self._caches
            ]

        def sample(logits, rng, temps):
            # logits [B, V] float32; temps [B]; 0 = greedy.
            greedy = jnp.argmax(logits, axis=-1)
            gumbel = jax.random.gumbel(rng, logits.shape)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            sampled = jnp.argmax(scaled + gumbel, axis=-1)
            return jnp.where(temps <= 0.0, greedy,
                             sampled).astype(jnp.int32)

        k_steps = self.steps_per_call

        def decode_fn(variables, caches, tokens, positions, rng, temps):
            """K decode steps in ONE device dispatch (lax.scan): on a
            high-RTT link each host round trip costs ~an RTT, so
            single-token stepping caps tokens/s at 1/RTT per wave;
            scanning K steps on device multiplies that by K.  Tokens
            feed forward on device; the host sees [S, K] at once (stop
            conditions checked per chunk — at most K-1 wasted steps
            after an EOS/budget stop)."""
            def step(carry, step_rng):
                caches, tokens, positions = carry
                logits, new_caches = module.apply(
                    variables, tokens[:, None], positions=positions,
                    kv_cache=caches)
                nxt = sample(logits[:, 0], step_rng, temps)
                return (new_caches, nxt, positions + 1), nxt

            rngs = jax.random.split(rng, k_steps)
            (caches, _, _), toks = jax.lax.scan(
                step, (caches, tokens, positions), rngs)
            return toks.T, caches  # [S, K]

        # Donate the caches: in-place HBM update, one resident pool.
        self._decode = jax.jit(decode_fn, donate_argnums=(1,))

        def prefill_fn(variables, ids, lengths, rng, temps):
            logits, caches = module.apply(variables, ids,
                                          kv_lengths=lengths,
                                          return_cache=True)
            idx = (lengths - 1)[:, None, None]
            last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
            first_tokens = sample(last, rng, temps)
            return first_tokens, caches

        # One executable per prompt bucket (jit caches by shape).
        self._prefill = jax.jit(prefill_fn)

        def insert_fn(caches, new_caches, slots):
            """Scatter a prefill batch's k/v into its slots.  slots is
            [B] int32; padding rows carry the out-of-bounds sentinel
            max_slots and mode='drop' discards them (a prefill batch is
            padded to a pow2 B bucket to bound compile count)."""
            out = []
            for (k_cache, v_cache), (k_new, v_new) in zip(caches,
                                                          new_caches):
                lb = k_new.shape[1]
                out.append((
                    k_cache.at[slots, :lb].set(
                        k_new.astype(k_cache.dtype), mode="drop"),
                    v_cache.at[slots, :lb].set(
                        v_new.astype(v_cache.dtype), mode="drop"),
                ))
            return out

        self._insert = jax.jit(insert_fn, donate_argnums=(0,))

        # Single worker: device steps are sequential by design; the
        # executor keeps them off the asyncio serving loop.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"generator-{name}")
        self._slots: List[Optional[_Active]] = [None] * self.max_slots
        self._pending: deque = deque()
        self._wakeup: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False

        # stats
        self.tokens_generated = 0
        self.decode_steps = 0       # device dispatches
        self._token_steps = 0       # dispatches x steps_per_call
        self.prefills = 0           # prefill dispatches
        self.prefill_requests = 0   # requests admitted through them
        self.requests_finished = 0
        self._occupied_slot_steps = 0
        self._decode_device_s = 0.0
        self._prefill_device_s = 0.0

    # -- public API --------------------------------------------------------
    def cache_bytes(self) -> int:
        per_buf = int(np.prod(self._cache_shape)) * \
            np.dtype(self._cache_dtype).itemsize
        return per_buf * 2 * len(self._caches)

    def param_bytes(self) -> int:
        jax = self._jax
        return sum(int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
                   for x in jax.tree.leaves(self.variables))

    async def generate(self, prompt_ids, max_new_tokens: int = 32,
                       temperature: float = 0.0
                       ) -> AsyncIterator[Tuple[int, Optional[str]]]:
        """Yields (token_id, finish_reason) events.  Intermediate
        tokens arrive as (id, None); the stream ends with either
        (id, 'length') — the budget-final token — or (None, 'eos'),
        since EOS is a stop signal, not content.  Engine failures
        surface as InferenceError mid-stream."""
        req = self.submit(prompt_ids, max_new_tokens, temperature)
        async for event in self.stream(req):
            yield event

    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0) -> _Request:
        """Validate and enqueue a request NOW (InvalidInput surfaces to
        the caller before any response bytes are committed — the
        streaming route depends on this).  Pair with `stream()`."""
        return self._submit(prompt_ids, max_new_tokens, temperature)

    async def stream(self, req: _Request
                     ) -> AsyncIterator[Tuple[Optional[int],
                                              Optional[str]]]:
        while True:
            token, reason = await req.out.get()
            if reason is not None and reason.startswith("error"):
                raise InferenceError(reason)
            yield token, reason
            if reason is not None:
                return

    def cancel(self, req: _Request) -> None:
        """Abandon a request: a consumer that stops caring (client
        disconnect, stop-sequence match) must free the decode slot —
        otherwise the engine decodes to the full token budget for
        nobody.  Runs on the event loop thread (the same thread as all
        slot bookkeeping).  Idempotent; a finished request is a no-op.
        The slot stops being fed at the next wave boundary."""
        if req.cancelled:
            return
        req.cancelled = True
        try:
            self._pending.remove(req)
            req.out.put_nowait((None, "cancelled"))
            return
        except ValueError:
            pass
        for i, s in enumerate(self._slots):
            if s is not None and s.req is req:
                self._slots[i] = None
                self.requests_finished += 1
                req.out.put_nowait((None, "cancelled"))
                return
        # Neither pending nor active: either already finished (no-op)
        # or mid-prefill on the executor — the install step checks
        # `cancelled` and drops it.

    async def complete(self, prompt_ids, max_new_tokens: int = 32,
                       temperature: float = 0.0
                       ) -> Tuple[List[int], str]:
        tokens: List[int] = []
        reason = "length"
        async for token, fin in self.generate(prompt_ids,
                                              max_new_tokens,
                                              temperature):
            if token is not None:
                tokens.append(token)
            if fin is not None:
                reason = fin
        return tokens, reason

    def _submit(self, prompt_ids, max_new_tokens, temperature) -> _Request:
        if self._closed:
            raise InvalidInput(f"generator {self.name} is closed")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise InvalidInput("empty prompt")
        if ids.size > self.prefill_buckets[-1]:
            raise InvalidInput(
                f"prompt length {ids.size} exceeds the largest prefill "
                f"bucket {self.prefill_buckets[-1]}")
        if max_new_tokens < 1:
            raise InvalidInput("max_new_tokens must be >= 1")
        # Clamp the budget to cache capacity: prompt + generated tokens
        # must fit max_seq.
        budget = min(int(max_new_tokens), self.max_seq - int(ids.size))
        if budget < 1:
            raise InvalidInput(
                f"prompt length {ids.size} leaves no room to generate "
                f"within max_seq {self.max_seq}")
        req = _Request(ids, budget, float(temperature))
        self._pending.append(req)
        self._ensure_loop()
        return req

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._wakeup = asyncio.Event()
            self._loop_task = asyncio.get_running_loop().create_task(
                self._run())
        self._wakeup.set()

    async def close(self):
        self._closed = True
        if self._loop_task is not None:
            if self._wakeup is not None:
                self._wakeup.set()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
        self._executor.shutdown(wait=True)

    def shutdown_nowait(self):
        """Synchronous best-effort teardown (repository unload runs
        outside async context): stop admitting, let the scheduler task
        drain, release the worker thread without joining."""
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        self._executor.shutdown(wait=False)

    def stats(self) -> Dict[str, Any]:
        steps = max(1, self._token_steps)
        return {
            "tokens_generated": self.tokens_generated,
            "decode_steps": self.decode_steps,
            "token_steps": self._token_steps,
            "steps_per_call": self.steps_per_call,
            "prefills": self.prefills,
            "prefill_requests": self.prefill_requests,
            "requests_finished": self.requests_finished,
            "slot_occupancy": round(
                self._occupied_slot_steps / (steps * self.max_slots), 4),
            "max_slots": self.max_slots,
            "max_seq": self.max_seq,
            "cache_bytes": self.cache_bytes(),
            "decode_device_s": round(self._decode_device_s, 4),
            "prefill_device_s": round(self._prefill_device_s, 4),
        }

    # -- scheduler ---------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _next_rng(self):
        jax = self._jax
        self._step_counter += 1
        return jax.random.fold_in(self._rng, self._step_counter)

    async def _run(self):
        try:
            await self._run_inner()
        except Exception as e:  # decode/device failure: global
            logger.exception("generation scheduler failed")
            self._fail_all(f"error: generation failed: {e}")
        finally:
            # A close()/unload() with work in flight must not strand
            # awaiters on queues that will never receive a terminal
            # event.
            if self._closed:
                self._fail_all("error: generator closed")

    def _fail_all(self, reason: str):
        for i, s in enumerate(self._slots):
            if s is not None:
                s.req.out.put_nowait((None, reason))
                self._slots[i] = None
        while self._pending:
            self._pending.popleft().out.put_nowait((None, reason))

    def _bucket_for(self, n: int) -> int:
        return next(b for b in self.prefill_buckets if b >= n)

    def _take_prefill_group(self
                            ) -> Tuple[List[_Request], List[int], int]:
        """Pop the front run of pending requests that share a prefill
        bucket, up to the free slot count — they ride ONE prefill
        dispatch.  Strict FIFO: a different-bucket request at the front
        is never jumped.  Returns (group, slots, bucket)."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        group: List[_Request] = []
        bucket = 0
        while self._pending and len(group) < len(free):
            b = self._bucket_for(self._pending[0].prompt_ids.size)
            if not group:
                bucket = b
            elif b != bucket:
                break
            group.append(self._pending.popleft())
        return group, free[:len(group)], bucket

    async def _run_inner(self):
        loop = asyncio.get_event_loop()
        while not self._closed:
            admitted = False
            while self._pending and self._free_slot() is not None:
                group, slots, bucket = self._take_prefill_group()
                try:
                    firsts = await loop.run_in_executor(
                        self._executor, self._do_prefill_group,
                        group, slots, bucket)
                except Exception as e:
                    # A prefill failure (e.g. OOM compiling a new
                    # bucket) fails THAT group; in-flight slots keep
                    # decoding.
                    logger.exception("prefill failed")
                    for req in group:
                        req.out.put_nowait(
                            (None, f"error: prefill failed: {e}"))
                    continue
                # Slot bookkeeping and token delivery happen here on
                # the loop thread: asyncio.Queue is not thread-safe.
                for req, slot, first in zip(group, slots, firsts):
                    if req.cancelled:
                        # Cancelled while its prefill was on the
                        # executor: drop it before it occupies a slot.
                        # cancel() could not emit the terminal event
                        # for this request (it was neither pending nor
                        # active at that moment) — deliver it here or
                        # a consumer draining stream(req) hangs.
                        req.out.put_nowait((None, "cancelled"))
                        self.requests_finished += 1
                        continue
                    self._slots[slot] = _Active(
                        req=req, length=req.prompt_ids.size,
                        last_token=first, generated=0)
                    self._emit(slot, first)
                admitted = True
            active = [i for i, s in enumerate(self._slots)
                      if s is not None]
            if not active:
                if not self._pending:
                    self._wakeup.clear()
                    if admitted:
                        continue
                    try:
                        await asyncio.wait_for(self._wakeup.wait(),
                                               timeout=1.0)
                    except asyncio.TimeoutError:
                        if not self._pending and not any(
                                s is not None for s in self._slots):
                            return  # idle: let the loop die; resubmit restarts
                continue
            tokens = await loop.run_in_executor(
                self._executor, self._do_decode_step)
            self._distribute(tokens)

    def _do_prefill_group(self, group: List[_Request],
                          slots: List[int],
                          bucket: int) -> List[int]:
        """Runs on the executor thread: one bucket-padded prefill
        dispatch for the WHOLE group (a burst of arrivals used to pay
        one ~RTT dispatch each — half the device time under load).
        The batch pads to a pow2 row bucket so compile count stays
        bounded; padding rows carry an out-of-bounds slot sentinel the
        insert scatter drops.  Returns the first generated token per
        request; slot state is installed by the scheduler on the loop
        thread."""
        jnp = self._jnp
        b = len(group)
        b_bucket = 1
        while b_bucket < b:
            b_bucket *= 2
        ids = np.zeros((b_bucket, bucket), np.int32)
        lengths = np.ones(b_bucket, np.int32)  # dummy rows: length 1
        temps = np.zeros(b_bucket, np.float32)
        slot_arr = np.full(b_bucket, self.max_slots, np.int32)  # OOB
        for i, (req, slot) in enumerate(zip(group, slots)):
            n = req.prompt_ids.size
            ids[i, :n] = req.prompt_ids
            lengths[i] = n
            temps[i] = req.temperature
            slot_arr[i] = slot
        t0 = time.perf_counter()
        firsts, new_caches = self._prefill(
            self.variables, jnp.asarray(ids), jnp.asarray(lengths),
            self._next_rng(), jnp.asarray(temps))
        self._caches = self._insert(self._caches, new_caches,
                                    jnp.asarray(slot_arr))
        firsts = np.asarray(self._jax.block_until_ready(firsts))
        self._prefill_device_s += time.perf_counter() - t0
        self.prefills += 1
        self.prefill_requests += b
        return [int(firsts[i]) for i in range(b)]

    def _do_decode_step(self) -> np.ndarray:
        """One device dispatch = steps_per_call decode steps; returns
        [S, K] tokens."""
        jnp = self._jnp
        tokens = np.zeros(self.max_slots, np.int32)
        positions = np.zeros(self.max_slots, np.int32)
        temps = np.zeros(self.max_slots, np.float32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tokens[i] = s.last_token
            positions[i] = s.length
            temps[i] = s.req.temperature
        t0 = time.perf_counter()
        next_tokens, self._caches = self._decode(
            self.variables, self._caches, jnp.asarray(tokens),
            jnp.asarray(positions), self._next_rng(),
            jnp.asarray(temps))
        out = np.asarray(self._jax.block_until_ready(next_tokens))
        self._decode_device_s += time.perf_counter() - t0
        return out

    def _emit(self, slot: int, token: int):
        """Account a newly produced token for `slot` and deliver it (or
        the finish marker) to the request's stream.

        Invariant: `length` counts tokens whose k/v are IN the cache;
        `last_token` is the token the next decode step feeds at
        position `length`.  The produced token's k/v are NOT in the
        cache yet — the step that consumes it writes them (so this
        method never touches `length`)."""
        s = self._slots[slot]
        s.generated += 1
        self.tokens_generated += 1
        finished = None
        if self.eos_id is not None and token == self.eos_id:
            finished = "eos"
        elif s.generated >= s.req.max_new_tokens:
            finished = "length"
        if finished == "eos":
            # EOS is a stop signal, not content.
            s.req.out.put_nowait((None, "eos"))
        else:
            s.req.out.put_nowait((token, finished))
        if finished is not None:
            self._slots[slot] = None
            self.requests_finished += 1
        else:
            s.last_token = token

    def _distribute(self, tokens: np.ndarray):
        """tokens [S, K]: per active slot, consume the chunk in order;
        a slot finishing mid-chunk (EOS or budget) discards its
        remaining positions — at most K-1 device steps of waste."""
        self.decode_steps += 1
        k = tokens.shape[1]
        self._token_steps += k
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            self._occupied_slot_steps += k
            for j in range(k):
                if self._slots[i] is None:
                    break  # finished mid-chunk
                # Each scanned step wrote the fed token's k/v at the
                # slot's position: the cache grew by one per step.
                s.length += 1
                self._emit(i, int(tokens[i, j]))


def _pow2_buckets(max_seq: int) -> List[int]:
    out, b = [], 16
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return out
