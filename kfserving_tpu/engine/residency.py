"""Demand-paged model residency: host-resident always, HBM on demand.

KFServing's multi-model story packs many models onto one scarce
accelerator (TrainedModel CRD + agent puller, PAPER.md §control
plane); on TPU "loaded" means *resident in HBM*, the resource
`engine/hbm.py` accounts.  This manager makes that residency
demand-paged, TF-Serving-aspired-versions style generalized from two
versions of one model to N models (arxiv 1712.06139):

- REGISTRATION is declarative and cheap: a registered model is
  addressable and `ready` but owns no device memory.  Host params are
  mmap-backed (engine/param_cache.py — PR 7 made them free to keep),
  so the whole repository stays host-resident.
- HBM residency is a managed LRU cache over the HBMManager ledger.  A
  request to a non-resident model transparently FAULTS it in: the
  first activation pays the cold build (download + materialize +
  compile); every later fault is one device_put off the mmap views —
  milliseconds, no recompile (the jit cache keys on shapes).
- Fault-ins are SINGLE-FLIGHT: concurrent requests to the same
  non-resident model coalesce onto one transfer (counted as
  `outcome="coalesced"`).
- Eviction is ADMISSION-AWARE: a model with queued or in-flight work
  is never a victim (`HBMManager.victim_ok` veto, counted in
  `kfserving_tpu_hbm_eviction_skips_total`); victims come from the
  ledger's LRU order, which the predict path touches on every request.
  A victim is *claimed* under the ledger lock, so a fault-in racing an
  eviction of the same model serializes instead of serving a
  half-evicted model.
- A failed fault-in (chaos site `engine.residency_swap`, storage
  errors, OOM) leaves the incumbent resident set serving: the
  admission plan is transactional, the faulting model returns to its
  prior state, and the error surfaces to the requester alone.

States: registered -> (cold fault) -> resident <-> (evict/warm fault)
host.  Observability: `kfserving_tpu_residency_*` families, timeline
events (`residency.fault_in` / `residency.evict`), and a
flight-recorder pin when evictions storm (`KFS_RESIDENCY_STORM_*`
knobs) — thrash evidence must survive the healthy traffic that
follows.
"""

import asyncio
import concurrent.futures
import contextlib
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from kfserving_tpu.engine.hbm import HBMManager, InsufficientHBM
from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.observability.profiling import TIMELINE
from kfserving_tpu.reliability import fault_sites
from kfserving_tpu.reliability.faults import FaultInjected, faults

logger = logging.getLogger("kfserving_tpu.residency")

STATE_CODES = {"registered": 0, "host": 1, "faulting": 2,
               "resident": 3, "evicting": 3}  # evicting is still in HBM

# A fault-in that finds every eviction candidate busy waits for one to
# free instead of failing the request (the admission-aware veto makes
# "no victim" a transient condition, not an error).
DEFAULT_ADMIT_WAIT_S = 5.0
# Eviction-storm pin: > threshold evictions inside the window pins a
# flight-recorder entry with the ledger snapshot (thrash evidence).
DEFAULT_STORM_WINDOW_S = 10.0
DEFAULT_STORM_THRESHOLD = 8


class _Record:
    __slots__ = ("name", "model", "state", "inflight", "nbytes",
                 "fault", "fault_counts", "last_fault_ms")

    def __init__(self, name: str, model: Any):
        self.name = name
        self.model = model
        # "registered" (no engine yet) | "host" (engine built, params
        # offloaded) | "faulting" | "resident" | "evicting" (claimed by
        # an admission plan, still physically in HBM)
        self.state = "registered" if not getattr(model, "ready", False) \
            or getattr(model, "engine", None) is None else "resident"
        self.inflight = 0
        self.nbytes = 0
        self.fault: Optional[asyncio.Task] = None
        self.fault_counts = {"cold": 0, "warm": 0, "coalesced": 0,
                             "error": 0}
        self.last_fault_ms = 0.0


class ResidencyManager:
    """Owns the host<->HBM lifecycle for N registered models over one
    HBMManager.  Managed models must provide: blocking ``load()``
    (cold build; admits its own HBM), blocking ``fault_in()`` (warm
    device restore), ``offload()`` (drop device residency),
    ``host_bytes()`` and ``offloadable`` (see JaxModel)."""

    def __init__(self, hbm: HBMManager,
                 admit_wait_s: Optional[float] = None,
                 storm_window_s: Optional[float] = None,
                 storm_threshold: Optional[int] = None):
        self.hbm = hbm
        hbm.evict_cb = self._evict
        hbm.victim_ok = self._victim_ok
        hbm.victim_release = self._victim_release
        self.admit_wait_s = admit_wait_s if admit_wait_s is not None \
            else float(os.environ.get("KFS_RESIDENCY_ADMIT_WAIT_S",
                                      DEFAULT_ADMIT_WAIT_S))
        self.storm_window_s = storm_window_s if storm_window_s is not None \
            else float(os.environ.get("KFS_RESIDENCY_STORM_WINDOW_S",
                                      DEFAULT_STORM_WINDOW_S))
        self.storm_threshold = int(
            storm_threshold if storm_threshold is not None
            else float(os.environ.get("KFS_RESIDENCY_STORM_THRESHOLD",
                                      DEFAULT_STORM_THRESHOLD)))
        self._models: Dict[str, _Record] = {}
        # Dedicated fault-in workers: a fault must not queue behind N
        # resident models' engine executions on the shared default
        # executor — that queueing delay would land INSIDE the
        # measured fault-in latency (and the <100 ms warm bar).
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="residency")
        # Guards record state/inflight transitions.  Lock order: the
        # HBM ledger lock is OUTER (victim_ok/release run under it);
        # nothing here takes the ledger lock while holding this one.
        self._lock = threading.Lock()
        self._flight_recorder = None
        self._evict_times: deque = deque(maxlen=256)
        self._storm_pinned_at = 0.0
        # Bounded recent warm fault-in latencies (bench/debug p99).
        self.fault_ms: Dict[str, deque] = {
            "warm": deque(maxlen=512), "cold": deque(maxlen=512)}

    # -- registration ------------------------------------------------------
    def register(self, name: str, model: Any) -> None:
        """Declarative registration: the model joins the managed set in
        whatever state it is already in (a pre-loaded model registers
        as resident; a host-prepped one as registered — first predict
        cold-faults it)."""
        rec = self._models.get(name)
        if rec is not None and rec.model is model:
            return
        rec = _Record(name, model)
        if rec.state == "resident":
            rec.nbytes = self._host_bytes(model)
        self._models[name] = rec
        self._publish_state(rec)

    def deregister(self, name: str) -> None:
        self._models.pop(name, None)
        obs.residency_state().prune(model=name)

    def registered(self):
        return list(self._models)

    def state_of(self, name: str) -> Optional[str]:
        rec = self._models.get(name)
        return rec.state if rec is not None else None

    @staticmethod
    def _host_bytes(model) -> int:
        fn = getattr(model, "host_bytes", None)
        return int(fn()) if fn is not None else 0

    def _publish_state(self, rec: _Record) -> None:
        obs.residency_state().labels(model=rec.name).set(
            float(STATE_CODES.get(rec.state, 0)))

    def attach_flight_recorder(self, recorder) -> None:
        """Eviction-storm pins land here (the serving ModelServer
        attaches its monitoring recorder at start)."""
        self._flight_recorder = recorder

    def close(self) -> None:
        """Release the fault-in workers (server shutdown)."""
        self._executor.shutdown(wait=False)

    # -- request gate ------------------------------------------------------
    @contextlib.asynccontextmanager
    async def serving(self, name: str):
        """The predict-path gate: counts the request as in-flight
        (protecting the model from eviction — queued work included,
        the counter is held across the batcher wait), faults the model
        in when non-resident, and touches the LRU ledger so victims
        reflect USE order, not load order."""
        rec = self._models.get(name)
        if rec is None:
            yield
            return
        with self._lock:
            rec.inflight += 1
        try:
            await self.ensure_resident(name)
            yield
        finally:
            with self._lock:
                rec.inflight -= 1

    async def ensure_resident(self, name: str) -> None:
        """Fault `name` into HBM if needed (single-flight); fast path
        is one lock acquisition + an LRU touch."""
        rec = self._models.get(name)
        if rec is None:
            return
        with self._lock:
            resident = rec.state == "resident"
        if resident:
            self.hbm.touch(name)
            return
        loop = asyncio.get_running_loop()
        fault = rec.fault
        if fault is None or fault.done():
            fault = rec.fault = loop.create_task(self._fault_in(rec))
        else:
            rec.fault_counts["coalesced"] += 1
            obs.residency_fault_ins_total().labels(
                model=name, outcome="coalesced").inc()
        # shield: one cancelled requester must not kill the transfer
        # its coalesced peers are waiting on.
        await asyncio.shield(fault)
        self.hbm.touch(name)

    async def _fault_in(self, rec: _Record) -> None:
        loop = asyncio.get_running_loop()
        # Claim the record for the fault.  Only an UNCLAIMED state
        # (registered/host) can transition to faulting: a concurrent
        # admit that claimed this model as a victim (state=evicting)
        # owns the device until its physical offload lands — waiting
        # here is what makes fault-in-vs-eviction of the same model
        # ordered instead of interleaving restore with offload.
        source = None
        while source is None:
            with self._lock:
                if rec.state == "resident":
                    return  # an earlier fault (or load) already won
                if rec.state in ("registered", "host"):
                    source = ("cold" if rec.state == "registered"
                              else "warm")
                    rec.state = "faulting"
            if source is None:
                await asyncio.sleep(0.005)
        self._publish_state(rec)
        t0 = time.perf_counter()
        try:
            if faults.configured(fault_sites.ENGINE_RESIDENCY_SWAP):
                await faults.inject(
                    fault_sites.ENGINE_RESIDENCY_SWAP,
                    key=f"{rec.name} source:{source}")
            work = (rec.model.load if source == "cold"
                    else lambda: self._admit_and_restore(rec))
            # Admission-aware eviction can transiently find every
            # candidate busy — wait for one to free, bounded.
            until = loop.time() + self.admit_wait_s
            while True:
                try:
                    await loop.run_in_executor(self._executor, work)
                    break
                except InsufficientHBM as e:
                    # Permanent = bigger than the whole budget: no
                    # eviction will ever make it fit — waiting out the
                    # admit window would burn an executor worker per
                    # predict for nothing.
                    if e.permanent or loop.time() >= until:
                        raise
                    await asyncio.sleep(0.02)
            with self._lock:
                rec.state = "resident"
            rec.nbytes = self._host_bytes(rec.model) or rec.nbytes
        except BaseException as e:
            # The incumbent resident set is untouched (the admission
            # plan is transactional and the injection site sits before
            # it); only THIS model returns to its prior state.  The
            # fault's admission episode is over: close its skip-dedup
            # window so a later retry counts busy victims afresh.
            self.hbm.end_skip_episode(rec.name)
            with self._lock:
                rec.state = "registered" if source == "cold" else "host"
            rec.fault_counts["error"] += 1
            obs.residency_fault_ins_total().labels(
                model=rec.name, outcome="error").inc()
            self._publish_state(rec)
            if isinstance(e, (FaultInjected, InsufficientHBM)):
                logger.warning("fault-in of %s failed (%s); incumbent "
                               "resident set keeps serving", rec.name, e)
            else:
                logger.exception("fault-in of %s failed", rec.name)
            raise
        finally:
            rec.fault = None
        dur_s = time.perf_counter() - t0
        rec.last_fault_ms = dur_s * 1e3
        rec.fault_counts[source] += 1
        self.fault_ms[source].append(dur_s * 1e3)
        obs.residency_fault_in_ms().labels(source=source).observe(
            dur_s * 1e3)
        obs.residency_fault_ins_total().labels(
            model=rec.name, outcome=source).inc()
        TIMELINE.record("host", "residency.fault_in", dur_s=dur_s,
                        attrs={"model": rec.name, "source": source})
        self._publish_state(rec)
        logger.info("faulted %s into HBM (%s, %.1f ms)",
                    rec.name, source, dur_s * 1e3)

    def _admit_and_restore(self, rec: _Record) -> None:
        """Warm fault body (executor thread): claim the bytes in the
        ledger (evicting admission-approved victims), then re-place
        the mmap views on device.  A failed restore releases the
        claim."""
        nbytes = rec.nbytes or self._host_bytes(rec.model)
        self.hbm.admit(rec.name, nbytes)
        try:
            rec.model.fault_in()
        except BaseException:
            self.hbm.release(rec.name)
            raise

    # -- eviction (HBMManager callbacks) -----------------------------------
    def _victim_ok(self, name: str) -> bool:
        """Admission-aware veto + claim, called UNDER the ledger lock:
        only an idle, fully-resident, offloadable model can be a
        victim, and saying yes claims it (state -> evicting) so a
        racing fault-in/predict serializes on the ledger."""
        rec = self._models.get(name)
        if rec is None:
            return True  # unmanaged entry (staging keys, legacy path)
        with self._lock:
            if rec.inflight > 0 or rec.state != "resident":
                return False
            rec.state = "evicting"
            return True

    def _victim_release(self, name: str) -> None:
        rec = self._models.get(name)
        if rec is None:
            return
        with self._lock:
            if rec.state == "evicting":
                rec.state = "resident"

    def _evict(self, name: str) -> None:
        """Physical offload of a committed victim (ledger already
        updated by admit).  Offloadable models keep their warm engine
        shell + host mmap params (warm re-fault in milliseconds); a
        model without a host restore source (param cache disabled,
        mesh-sharded) is demoted all the way to registered — its next
        fault is a cold rebuild."""
        rec = self._models.get(name)
        if rec is None:
            return
        offloaded = False
        try:
            if getattr(rec.model, "offloadable", False):
                rec.model.offload()
                offloaded = True
            else:
                demote = getattr(rec.model, "demote", None)
                if demote is not None:
                    demote()
        finally:
            with self._lock:
                rec.state = "host" if offloaded else "registered"
            self._publish_state(rec)
        TIMELINE.record("host", "residency.evict",
                        attrs={"model": name, "bytes": rec.nbytes,
                               "warm": offloaded})
        logger.info("evicted %s from HBM (%s)", name,
                    "host params retained" if offloaded
                    else "demoted to registered")
        self._note_eviction()

    def _note_eviction(self) -> None:
        now = time.monotonic()
        self._evict_times.append(now)
        recent = sum(1 for t in self._evict_times
                     if now - t <= self.storm_window_s)
        if recent <= self.storm_threshold:
            return
        recorder = self._flight_recorder
        # One pin per storm window, not one per eviction in it.
        if recorder is None or \
                now - self._storm_pinned_at < self.storm_window_s:
            return
        self._storm_pinned_at = now
        recorder.record({
            "kind": "residency_eviction_storm",
            "evictions_in_window": recent,
            "window_s": self.storm_window_s,
            "hbm": self.hbm.debug(),
            "residency": self.debug(),
        }, pin="eviction_storm")
        logger.warning(
            "HBM eviction storm: %d evictions in %.0fs (working set "
            "exceeds the budget — flight-recorder entry pinned)",
            recent, self.storm_window_s)

    # -- introspection -----------------------------------------------------
    def debug(self) -> Dict[str, Any]:
        """The `/debug/cache` residency block, federated by the router
        under the replica label."""
        def pct(values, q):
            if not values:
                return None
            ordered = sorted(values)
            return round(ordered[min(len(ordered) - 1,
                                     int(len(ordered) * q))], 3)

        with self._lock:
            models = {
                name: {"state": rec.state, "inflight": rec.inflight,
                       "nbytes": rec.nbytes,
                       "fault_ins": dict(rec.fault_counts),
                       "last_fault_ms": round(rec.last_fault_ms, 3)}
                for name, rec in self._models.items()}
        warm = list(self.fault_ms["warm"])
        cold = list(self.fault_ms["cold"])
        return {
            "registered": len(models),
            "resident": sum(1 for m in models.values()
                            if m["state"] in ("resident", "evicting")),
            "models": models,
            "fault_in_ms": {
                "warm_p50": pct(warm, 0.50), "warm_p99": pct(warm, 0.99),
                "cold_p50": pct(cold, 0.50), "cold_p99": pct(cold, 0.99),
                "warm_count": len(warm), "cold_count": len(cold),
            },
            "evictions_total": sum(self.hbm.evictions.values()),
            "eviction_skips_total": sum(
                self.hbm.eviction_skips.values()),
        }
