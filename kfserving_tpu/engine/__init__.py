from kfserving_tpu.engine.buckets import BucketPolicy
from kfserving_tpu.engine.jax_engine import JaxEngine

__all__ = ["JaxEngine", "BucketPolicy"]
