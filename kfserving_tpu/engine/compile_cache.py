"""Persistent XLA compilation cache + warmup helpers.

TPU cold start = pod start + model download + XLA compile.  The reference
leans on the Knative activator for scale-from-zero buffering (reference
test/benchmark/README.md:14-17); the TPU-native mitigation is a persistent
compilation cache on disk so restarts skip recompiles (SURVEY.md §5.3), plus
engine warmup tied into the readiness probe.
"""

import logging
import os
from typing import Optional

logger = logging.getLogger("kfserving_tpu.compile_cache")

DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/kfserving_tpu/xla")

_active_dir: Optional[str] = None


def note_compilation(source: str, key) -> None:
    """Every engine reports its first-dispatch-per-shape here (the
    JaxEngine bucket grid, the generator's decode/prefill/chunk
    programs).  This module is the funnel because compilation policy
    lives here: today the note feeds the KFS_SANITIZE recompile
    assertion (a compile after `source`'s declared warmup is a
    violation); a disabled sanitizer makes this one env read."""
    from kfserving_tpu.reliability import sanitizer

    sanitizer.note_compilation(source, key)


def declare_warmup_complete(source: str) -> None:
    """Engines call this when their warmup grid is fully compiled;
    from then on a note_compilation() for `source` is a sanitizer
    violation (KFS_SANITIZE=1) instead of expected behavior."""
    from kfserving_tpu.reliability import sanitizer

    sanitizer.declare_warmup_complete(source)


def enable(cache_dir: Optional[str] = None,
           min_compile_time_secs: float = 0.5) -> str:
    """Enable the JAX persistent compilation cache.

    Idempotent for the same directory; a later call with a *different*
    directory re-points the cache (and says so) rather than silently
    returning an inactive path.
    """
    global _active_dir
    cache_dir = cache_dir or os.environ.get(
        "KFSERVING_TPU_COMPILE_CACHE", DEFAULT_CACHE_DIR)
    if _active_dir == cache_dir:
        return cache_dir
    if _active_dir is not None:
        logger.warning("re-pointing XLA compile cache %s -> %s",
                       _active_dir, cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_time_secs)
    _active_dir = cache_dir
    # Marker on the engine event timeline: compile-miss slices after
    # this point are persistent-cache loads, not fresh XLA compiles.
    from kfserving_tpu.observability.profiling import TIMELINE

    TIMELINE.record("host", "compile_cache.enabled",
                    attrs={"dir": cache_dir})
    from kfserving_tpu.observability import REGISTRY

    REGISTRY.gauge(
        "kfserving_tpu_compile_cache_enabled",
        "1 when the persistent XLA compile cache is active").labels(
            dir=cache_dir).set(1)
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir
