"""Shape-bucket policy: which padded shapes get compiled, and how requests
map onto them.

XLA compiles one executable per input shape; serving arbitrary batch sizes /
sequence lengths would recompile constantly.  The policy quantizes dynamic
dimensions to a small set of buckets (compile once per bucket, pad to fit).
This is the TPU-native replacement for the reference batcher's single
max-batch knob (reference pkg/batcher/handler.go:32-36) — bucket boundaries
ARE the jit compile shapes (SURVEY.md §7 "hard parts").
"""

import bisect
from typing import List, Optional, Sequence


def pow2_buckets(max_value: int, min_value: int = 1) -> List[int]:
    out = []
    v = min_value
    while v < max_value:
        out.append(v)
        v *= 2
    out.append(max_value)
    return out


class BucketPolicy:
    """Quantize a dynamic dimension (batch or sequence length) to buckets."""

    def __init__(self, buckets: Sequence[int]):
        if not buckets:
            raise ValueError("buckets must be non-empty")
        self.buckets = sorted(set(int(b) for b in buckets))

    @classmethod
    def pow2(cls, max_value: int, min_value: int = 1) -> "BucketPolicy":
        return cls(pow2_buckets(max_value, min_value))

    @property
    def max(self) -> int:
        return self.buckets[-1]

    def fit(self, n: int) -> Optional[int]:
        """Smallest bucket >= n, or None if n exceeds the largest bucket."""
        i = bisect.bisect_left(self.buckets, n)
        if i == len(self.buckets):
            return None
        return self.buckets[i]

    def floor_fit(self, n: int) -> Optional[int]:
        """Largest bucket <= n, or None if n is below the smallest bucket.
        The batcher's bucket-aligned flush uses this: executing exactly a
        bucket's worth of pending instances pads zero slots."""
        i = bisect.bisect_right(self.buckets, n) - 1
        if i < 0:
            return None
        return self.buckets[i]

    def waste(self, n: int) -> float:
        """Fraction of padded work wasted for a size-n batch."""
        b = self.fit(n)
        if b is None:
            return 0.0
        return (b - n) / b
