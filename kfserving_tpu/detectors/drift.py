"""Distribution drift detection on served payloads (alibi-detect KS
sample parity).

The reference's drift sample runs alibi-detect's Kolmogorov-Smirnov
detector as a logger-fed service (reference docs/samples/
outlier-detection/alibi-detect: the cifar10 drift KService).  This is
the first-party equivalent: per-feature two-sample KS tests between a
reference sample and a sliding window of served instances, with
Bonferroni correction across features — closed-form numpy, no
alibi-detect dependency.

Artifact layout (`storage_uri`):
    train.npy    — [m, d] reference sample
    drift.json   — {"window": 128, "p_value": 0.05}  (optional)
"""

import json
import logging
import math
import os
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.detectors.drift")


def ks_statistic(a: np.ndarray, b: np.ndarray,
                 a_sorted: bool = False) -> float:
    """Two-sample Kolmogorov-Smirnov statistic (max ECDF distance).
    a_sorted=True skips re-sorting a static reference sample."""
    a = np.asarray(a, np.float64)
    if not a_sorted:
        a = np.sort(a)
    b = np.sort(np.asarray(b, np.float64))
    grid = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, grid, side="right") / len(a)
    cdf_b = np.searchsorted(b, grid, side="right") / len(b)
    return float(np.abs(cdf_a - cdf_b).max())


def ks_p_value(d: float, n: int, m: int) -> float:
    """Asymptotic two-sample KS p-value (Kolmogorov distribution with
    the Stephens small-sample correction, as scipy's asymp mode)."""
    if d <= 0:
        return 1.0
    en = math.sqrt(n * m / (n + m))
    lam = (en + 0.12 + 0.11 / en) * d
    total = 0.0
    for k in range(1, 101):
        term = (-1) ** (k - 1) * math.exp(-2.0 * (k * lam) ** 2)
        total += term
        if abs(term) < 1e-10:
            break
    return max(0.0, min(1.0, 2.0 * total))


def ks_drift_test(ref_sorted: np.ndarray, window: np.ndarray,
                  reference_len: int,
                  p_value: float) -> Dict[str, Any]:
    """Bonferroni-corrected per-feature KS drift verdict: the shared
    core of the served `KSDriftDetector` and the streaming
    `observability.monitoring.DriftMonitor` — one implementation, two
    deployment shapes.

    ref_sorted: [m, d] reference, column-sorted once at load/fit.
    window: [w, d] live sample.  Returns drift flag, per-feature
    p-values, the max KS statistic (the exported drift score), and
    the corrected threshold."""
    d = ref_sorted.shape[1]
    stats, p_values = [], []
    for j in range(d):
        stat = ks_statistic(ref_sorted[:, j], window[:, j],
                            a_sorted=True)
        stats.append(stat)
        p_values.append(ks_p_value(stat, reference_len, len(window)))
    threshold = p_value / d  # Bonferroni
    return {
        "drift": bool(min(p_values) < threshold),
        "score": float(max(stats)),
        "p_values": p_values,
        "threshold": threshold,
        "window": len(window),
    }


class KSDriftDetector(Model):
    """Sliding-window per-feature KS drift vs a reference sample.

    Each served payload appends to the window; once full, every event
    re-tests.  Bonferroni: drift when any feature's p-value falls below
    p_value / d (alibi-detect's default correction)."""

    def __init__(self, name: str, model_dir: str,
                 window: Optional[int] = None,
                 p_value: Optional[float] = None):
        super().__init__(name)
        self.model_dir = model_dir
        self._window_override = window
        self._p_override = p_value
        self.reference: Optional[np.ndarray] = None
        self.window: deque = deque()
        self.window_size = 128
        self.p_value = 0.05
        self.drift_events = 0
        self.last_result: Optional[Dict[str, Any]] = None

    def load(self) -> bool:
        from kfserving_tpu.storage import Storage

        local = Storage.download(self.model_dir)
        self.reference = np.asarray(
            np.load(os.path.join(local, "train.npy")), np.float64)
        if self.reference.ndim != 2:
            raise InvalidInput("drift reference must be [m, d]")
        cfg: Dict[str, Any] = {}
        cfg_path = os.path.join(local, "drift.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
        # `is not None` (not truthiness): an explicit override of 0 must
        # be rejected by the range checks below, not silently replaced
        # by the config default.
        self.window_size = int(
            self._window_override if self._window_override is not None
            else cfg.get("window", 128))
        self.p_value = float(
            self._p_override if self._p_override is not None
            else cfg.get("p_value", 0.05))
        if self.window_size < 1:
            raise InvalidInput(
                f"drift window must be >= 1, got {self.window_size}")
        if not 0.0 < self.p_value < 1.0:
            raise InvalidInput(
                f"drift p_value must be in (0, 1), got {self.p_value}")
        self.window = deque(maxlen=self.window_size)
        # Pre-sort the static reference once; re-test at a stride, not
        # per event (d KS tests over a high-dim payload per mirrored
        # request would stall the sink's event loop and drop payloads).
        self._ref_sorted = np.sort(self.reference, axis=0)
        self.test_stride = int(cfg.get(
            "test_stride", max(1, self.window_size // 16)))
        self._rows_since_test = 0
        self.ready = True
        return True

    async def predict(self, request: Any) -> Any:
        if self.reference is None:
            raise InvalidInput(f"detector {self.name} not loaded")
        if isinstance(request, dict) and "predictions" in request \
                and "instances" not in request and "inputs" not in request:
            return {"ignored": "response event"}
        try:
            instances = np.asarray(v1.get_instances(request), np.float64)
        except (ValueError, TypeError) as e:
            raise InvalidInput(f"non-numeric payload: {e}")
        if instances.ndim == 1:
            instances = instances[None]
        instances = instances.reshape(len(instances), -1)
        d = self.reference.shape[1]
        if instances.shape[1] != d:
            raise InvalidInput(
                f"instance dim {instances.shape[1]} != reference dim {d}")
        for row in instances:
            self.window.append(row)
        self._rows_since_test += len(instances)
        if len(self.window) < self.window_size:
            return {"drift": None,
                    "window_fill": len(self.window) / self.window_size}
        if self._rows_since_test < self.test_stride and \
                self.last_result is not None:
            return self.last_result
        self._rows_since_test = 0
        result = ks_drift_test(self._ref_sorted, np.stack(self.window),
                               len(self.reference), self.p_value)
        if result["drift"]:
            self.drift_events += 1
        result["p_values"] = [round(p, 6) for p in result["p_values"]]
        del result["score"]  # response-shape compatibility
        self.last_result = result
        return self.last_result

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        meta.update({"detector": "ks-drift",
                     "window_size": self.window_size,
                     "drift_events": self.drift_events})
        return meta
