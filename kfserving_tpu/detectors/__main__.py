"""`python -m kfserving_tpu.detectors` — standalone detector server.

Serve an outlier or drift detector and point an InferenceService's
`logger.url` at it (the reference runs the alibi-detect sample as a
KService sink for the payload logger):

    python -m kfserving_tpu.detectors \\
        --model_name cifar10-od --detector_type outlier \\
        --storage_uri file:///path/with/train.npy --http_port 8082

Then in the isvc spec: "logger": {"url": "http://host:8082/v1/models/
cifar10-od:predict", "mode": "request"}.
"""

import argparse
import logging

from kfserving_tpu.detectors import DETECTOR_TYPES, build_detector
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="detector")
parser.add_argument("--detector_type", default="outlier",
                    choices=DETECTOR_TYPES)
parser.add_argument("--storage_uri", required=True,
                    help="artifact dir with train.npy (+ optional "
                         "outlier.json / drift.json)")
parser.add_argument("--alert_url", default=None,
                    help="POST an alert CloudEvent here on detection "
                         "(outlier type only)")


def main(argv=None):
    args, _ = parser.parse_known_args(argv)
    model = build_detector(args.model_name, args.detector_type,
                           args.storage_uri, alert_url=args.alert_url)
    model.load()
    ModelServer(http_port=args.http_port,
                container_concurrency=args.container_concurrency
                ).start([model])


if __name__ == "__main__":
    main()
