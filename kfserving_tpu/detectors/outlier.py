"""Outlier detection on served payloads (alibi-detect sample parity).

The reference wires outlier detection as a separate service consuming
the payload logger's CloudEvents stream (reference
docs/samples/outlier-detection/alibi-detect/cifar10: a KService running
alibi-detect receives mirrored inference requests via `logger.url` and
emits alerts).  This is the first-party equivalent: a Mahalanobis
detector served as a Model — point an InferenceService's
`logger.url` at its `:predict` route and every request payload is
scored as it is served.

Artifact layout (`storage_uri`):
    train.npy      — [m, d] reference sample (fit: mean + covariance)
    outlier.json   — {"threshold_percentile": 99.5} or
                     {"threshold": 12.3}  (optional; percentile of the
                     train sample's own scores by default)
"""

import json
import logging
import os
import time
from typing import Any, Dict, Optional

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InvalidInput

logger = logging.getLogger("kfserving_tpu.detectors.outlier")


class MahalanobisScorer:
    """Closed-form Mahalanobis distance to a fitted Gaussian."""

    def __init__(self, train: np.ndarray, regularization: float = 1e-6):
        train = np.asarray(train, np.float64)
        if train.ndim != 2 or len(train) < 2:
            raise InvalidInput("outlier train data must be [m>=2, d]")
        self.mean = train.mean(axis=0)
        cov = np.cov(train, rowvar=False)
        cov = np.atleast_2d(cov)
        cov += regularization * np.eye(cov.shape[0])
        self.precision = np.linalg.inv(cov)

    def score(self, batch: np.ndarray) -> np.ndarray:
        """[n] Mahalanobis distances; rows flattened to the fitted d."""
        x = np.asarray(batch, np.float64).reshape(len(batch), -1)
        if x.shape[1] != self.mean.shape[0]:
            raise InvalidInput(
                f"instance dim {x.shape[1]} != fitted dim "
                f"{self.mean.shape[0]}")
        delta = x - self.mean
        return np.sqrt(np.einsum("ni,ij,nj->n", delta, self.precision,
                                 delta))


def fit_threshold(scorer: MahalanobisScorer, train: np.ndarray,
                  percentile: float = 99.5) -> float:
    """Threshold = a percentile of the train sample's own scores: the
    shared fit used by the served `OutlierDetector` and the streaming
    `observability.monitoring.OutlierMonitor`."""
    return float(np.percentile(scorer.score(
        np.asarray(train, np.float64)), percentile))


class OutlierDetector(Model):
    """Served detector: scores request payloads against the training
    distribution; responds (and counts) per-instance verdicts.

    As a logger sink it receives CloudEvents; response events
    (org.kubeflow.serving.inference.response) are acknowledged and
    skipped — only request payloads carry feature vectors."""

    def __init__(self, name: str, model_dir: str,
                 alert_url: Optional[str] = None):
        super().__init__(name)
        self.model_dir = model_dir
        self.alert_url = alert_url
        self.scorer: Optional[MahalanobisScorer] = None
        self.threshold: Optional[float] = None
        self.seen = 0
        self.flagged = 0
        self.alerts_sent = 0
        self.alert_errors = 0
        # Strong refs: the loop holds tasks weakly — an un-referenced
        # fire-and-forget alert can be GC'd mid-POST.
        self._alert_tasks: set = set()

    def load(self) -> bool:
        from kfserving_tpu.storage import Storage

        local = Storage.download(self.model_dir)
        train = np.load(os.path.join(local, "train.npy"))
        cfg: Dict[str, Any] = {}
        cfg_path = os.path.join(local, "outlier.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
        self.scorer = MahalanobisScorer(
            train, regularization=float(cfg.get("regularization", 1e-6)))
        if "threshold" in cfg:
            self.threshold = float(cfg["threshold"])
        else:
            self.threshold = fit_threshold(
                self.scorer, train,
                float(cfg.get("threshold_percentile", 99.5)))
        self.ready = True
        return True

    async def predict(self, request: Any) -> Any:
        if self.scorer is None:
            raise InvalidInput(f"detector {self.name} not loaded")
        # Logger response events carry predictions, not features.
        if isinstance(request, dict) and "predictions" in request \
                and "instances" not in request and "inputs" not in request:
            return {"ignored": "response event"}
        try:
            instances = np.asarray(v1.get_instances(request), np.float64)
        except (ValueError, TypeError) as e:
            # Ragged / non-numeric mirrored payloads are the sender's
            # shape, not a server fault.
            raise InvalidInput(f"non-numeric payload: {e}")
        if instances.ndim == 1:
            instances = instances[None]
        scores = self.scorer.score(instances)
        outliers = scores > self.threshold
        self.seen += len(scores)
        self.flagged += int(outliers.sum())
        if outliers.any() and self.alert_url:
            # Fire-and-forget: a slow alert broker must not stall the
            # logger sink (its workers await this response; a blocked
            # sink drops mirrored payloads).
            import asyncio

            task = asyncio.get_running_loop().create_task(
                self._alert(scores[outliers]))
            self._alert_tasks.add(task)
            task.add_done_callback(self._alert_tasks.discard)
        return {
            "outlier": outliers.astype(int).tolist(),
            "score": np.round(scores, 6).tolist(),
            "threshold": self.threshold,
        }

    async def _alert(self, scores: np.ndarray) -> None:
        """Emit an alert CloudEvent (the sample posts to a broker).
        Uses the inherited Model.http_session so close() cleans it up."""
        from kfserving_tpu.protocol import cloudevents

        try:
            event = cloudevents.new_event(
                "io.kfserving_tpu.detector.outlier",
                f"detector/{self.name}",
                {"count": int(len(scores)),
                 "max_score": float(scores.max()),
                 "threshold": self.threshold,
                 "ts": time.time()})
            headers, body = cloudevents.to_binary(event)
            async with self.http_session.post(
                    self.alert_url, data=body, headers=headers) as resp:
                await resp.read()
            self.alerts_sent += 1
        except Exception as e:  # alerting must never fail serving
            self.alert_errors += 1
            logger.warning("outlier alert to %s failed: %s",
                           self.alert_url, e)

    async def close(self) -> None:
        """Drain in-flight alerts before the session closes."""
        import asyncio

        if self._alert_tasks:
            await asyncio.gather(*list(self._alert_tasks),
                                 return_exceptions=True)
        await super().close()

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        meta.update({"detector": "mahalanobis", "seen": self.seen,
                     "flagged": self.flagged,
                     "threshold": self.threshold})
        return meta
