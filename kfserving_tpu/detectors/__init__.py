"""Payload detectors: outlier + drift monitoring on served traffic.

The reference ships these as alibi-detect samples fed by the payload
logger over Knative eventing (reference docs/samples/outlier-detection/
alibi-detect/cifar10).  Here they are first-party Models: deploy one as
a standalone server (`python -m kfserving_tpu.detectors`) and point an
InferenceService's `logger.url` at it — every mirrored request payload
gets scored as it is served.
"""

from kfserving_tpu.detectors.drift import (  # noqa: F401
    KSDriftDetector,
    ks_p_value,
    ks_statistic,
)
from kfserving_tpu.detectors.outlier import (  # noqa: F401
    MahalanobisScorer,
    OutlierDetector,
)

DETECTOR_TYPES = ("outlier", "drift")


def build_detector(name: str, detector_type: str, storage_uri: str,
                   alert_url=None):
    if detector_type == "outlier":
        return OutlierDetector(name, storage_uri, alert_url=alert_url)
    if detector_type == "drift":
        return KSDriftDetector(name, storage_uri)
    raise ValueError(
        f"unknown detector type {detector_type!r} "
        f"(one of {list(DETECTOR_TYPES)})")
