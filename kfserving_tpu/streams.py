"""Async-stream lifecycle utilities.

A plain async generator's ``finally`` only runs if the generator was
*started*: ``aclose()`` on a never-iterated generator marks it closed
without executing the body, so cleanup that lives in the body leaks
when the consumer abandons the stream before the first ``__anext__``
(e.g. a client that disconnects between submitting a generate_stream
request and the first response write).  Resource-holding streams
(admission slots, engine decode slots) wrap themselves in
``GuardedStream`` so their cleanup runs exactly once on every exit
path: exhaustion, mid-iteration error, ``aclose()`` after partial
iteration, and ``aclose()`` before any iteration at all.
"""

import inspect
import logging
from typing import Any, AsyncIterator, Callable

logger = logging.getLogger("kfserving_tpu.streams")


async def aclose_quietly(stream: Any, what: str = "stream") -> None:
    """Close an async iterator if it supports aclose(), swallowing (but
    logging) failures — the shared cleanup step for every consumer that
    must release a producer on an abnormal exit path."""
    aclose = getattr(stream, "aclose", None)
    if aclose is None:
        return
    try:
        await aclose()
    except Exception:
        logger.exception("closing %s failed", what)


class GuardedStream:
    """Wraps an async iterator; ``on_close`` runs exactly once when the
    stream ends for any reason.  ``on_close`` may be sync or async."""

    def __init__(self, gen: AsyncIterator[Any],
                 on_close: Callable[[], Any]):
        self._gen = gen
        self._on_close = on_close
        self._closed = False

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self._gen.__anext__()
        except StopAsyncIteration:
            await self._run_close()
            raise
        except BaseException:
            # The inner generator is already finalized by its own
            # exception propagation; run cleanup now rather than
            # relying on the consumer to aclose() a broken stream.
            await self._run_close()
            raise

    async def aclose(self):
        try:
            aclose = getattr(self._gen, "aclose", None)
            if aclose is not None:
                await aclose()
        finally:
            await self._run_close()

    async def _run_close(self):
        if self._closed:
            return
        self._closed = True
        try:
            result = self._on_close()
            if inspect.isawaitable(result):
                await result
        except Exception:
            logger.exception("stream on_close callback failed")
