"""Native tree-ensemble evaluators: xgboost-JSON and LightGBM-text.

The reference's xgbserver/lgbserver load models with the framework
libraries and predict on CPU (reference python/xgbserver/xgbserver/
model.py, python/lgbserver/lgbserver/model.py).  Those libraries are
optional here; this module evaluates the *public, documented artifact
formats* directly with numpy, so the predictors serve real models even
when the frameworks aren't installed (and the arrays are laid out so a
jax.jit gather walk is a drop-in upgrade for big ensembles).

Formats:
- xgboost >= 1.7 JSON (`booster.save_model("model.json")`): trees as
  parallel arrays `split_indices / split_conditions / left_children /
  right_children / default_left`; a node is a leaf when left_children[i]
  == -1, and `split_conditions` then holds the leaf value.  `tree_info`
  maps each tree to its output group (class).  base_score is stored in
  output space; it enters the margin through the objective's inverse
  link.
- LightGBM text (`booster.save_model("model.txt")`): per-tree blocks
  `split_feature / threshold / decision_type / left_child / right_child
  / leaf_value`; negative child ids are ~leaf references; tree k of a
  num_class=K model scores class k % K.

Both evaluators batch over rows: every tree is walked with vectorized
gathers (max tree depth iterations, no Python per-row loop).
"""

import json
import math
from typing import Any, Dict, List, Optional

import numpy as np


def _sigmoid(z: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-z))


def _softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


class _Tree:
    """One decision tree as parallel arrays (gather-walk evaluation)."""

    __slots__ = ("feature", "threshold", "left", "right", "default_left",
                 "is_leaf", "value")

    def __init__(self, feature, threshold, left, right, default_left,
                 is_leaf, value):
        self.feature = np.asarray(feature, np.int32)
        self.threshold = np.asarray(threshold, np.float64)
        self.left = np.asarray(left, np.int32)
        self.right = np.asarray(right, np.int32)
        self.default_left = np.asarray(default_left, bool)
        self.is_leaf = np.asarray(is_leaf, bool)
        self.value = np.asarray(value, np.float64)

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Vectorized walk: all rows descend together, one gather per
        level, until every row sits on a leaf."""
        n = X.shape[0]
        node = np.zeros(n, np.int32)
        active = ~self.is_leaf[node]
        while active.any():
            idx = node[active]
            feat = self.feature[idx]
            x = X[active, feat]
            missing = np.isnan(x)
            go_left = np.where(missing, self.default_left[idx],
                               x < self.threshold[idx])
            node[active] = np.where(go_left, self.left[idx],
                                    self.right[idx])
            active = ~self.is_leaf[node]
        return self.value[node]


class XGBoostEnsemble:
    """Evaluate an xgboost JSON model (cites reference xgbserver
    model.py:predict for the serving contract it replaces)."""

    def __init__(self, trees: List[_Tree], tree_groups: List[int],
                 num_class: int, base_score: float, objective: str):
        self.trees = trees
        self.tree_groups = tree_groups
        self.num_class = max(1, num_class)
        self.objective = objective
        # base_score is recorded in output space; margins accumulate in
        # link space, so invert the link once here.
        if objective.startswith(("binary:logistic", "reg:logistic")):
            base_score = min(max(base_score, 1e-7), 1 - 1e-7)
            self.base_margin = math.log(base_score / (1 - base_score))
        else:
            self.base_margin = base_score

    @classmethod
    def from_file(cls, path: str) -> "XGBoostEnsemble":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # Objectives whose output transform the evaluator implements.  Ranker
    # and squared-error objectives are identity in margin space; anything
    # with another inverse link (poisson/gamma/tweedie exp, etc.) must
    # raise at load rather than silently return link-space numbers.
    SUPPORTED_OBJECTIVES = (
        "binary:logistic", "reg:logistic", "multi:softprob",
        "multi:softmax", "reg:squarederror", "reg:squaredlogerror",
        "reg:linear", "reg:absoluteerror", "reg:pseudohubererror",
        "rank:pairwise", "rank:ndcg", "rank:map",
    )

    @classmethod
    def from_dict(cls, model: Dict[str, Any]) -> "XGBoostEnsemble":
        learner = model["learner"]
        booster = learner["gradient_booster"]
        if booster.get("name") not in (None, "gbtree"):
            # dart's JSON nests trees differently and needs weight_drop
            # scaling — reject rather than misparse.
            raise ValueError(
                f"unsupported booster {booster.get('name')!r} "
                f"(native evaluator handles gbtree)")
        objective = learner.get("objective", {}).get("name", "")
        if objective and objective not in cls.SUPPORTED_OBJECTIVES:
            raise ValueError(
                f"unsupported objective {objective!r}; native evaluator "
                f"handles {list(cls.SUPPORTED_OBJECTIVES)} — install "
                f"xgboost for others")
        gmodel = booster["model"]
        trees = []
        for t in gmodel["trees"]:
            left = np.asarray(t["left_children"], np.int32)
            trees.append(_Tree(
                feature=t["split_indices"],
                threshold=t["split_conditions"],
                left=left,
                right=t["right_children"],
                default_left=np.asarray(t["default_left"]) == 1,
                is_leaf=left < 0,
                # split_conditions holds the leaf value at leaf nodes
                value=t["split_conditions"],
            ))
        params = learner["learner_model_param"]
        return cls(
            trees=trees,
            tree_groups=[int(g) for g in gmodel.get(
                "tree_info", [0] * len(trees))],
            num_class=int(params.get("num_class", "0") or 0),
            base_score=float(params.get("base_score", "0.5")),
            objective=objective,
        )

    def margin(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float64)
        out = np.full((X.shape[0], self.num_class), self.base_margin)
        for tree, group in zip(self.trees, self.tree_groups):
            out[:, group] += tree.predict(X)
        return out

    def predict(self, X: np.ndarray, output_margin: bool = False
                ) -> np.ndarray:
        m = self.margin(X)
        if output_margin:
            return m[:, 0] if self.num_class == 1 else m
        if self.objective.startswith(("binary:logistic",)):
            return _sigmoid(m[:, 0])
        if self.objective.startswith("multi:softprob"):
            return _softmax(m)
        if self.objective.startswith("multi:softmax"):
            return np.argmax(m, axis=-1).astype(np.float64)
        return m[:, 0] if self.num_class == 1 else m


class LightGBMEnsemble:
    """Evaluate a LightGBM text model (reference lgbserver model.py)."""

    def __init__(self, trees: List[_Tree], num_class: int, objective: str):
        self.trees = trees
        self.num_class = max(1, num_class)
        self.objective = objective

    @classmethod
    def from_file(cls, path: str) -> "LightGBMEnsemble":
        with open(path) as f:
            return cls.from_text(f.read())

    @classmethod
    def from_text(cls, text: str) -> "LightGBMEnsemble":
        objective = ""
        num_class = 1
        trees: List[_Tree] = []
        block: Dict[str, str] = {}

        def finish_block():
            if "num_leaves" not in block:
                return
            num_leaves = int(block["num_leaves"])
            leaf_value = [float(v) for v in block["leaf_value"].split()]
            if num_leaves == 1:
                # Stump: a single leaf, no splits.
                trees.append(_Tree([0], [0.0], [-1], [-1], [True], [True],
                                   [leaf_value[0]]))
                return
            feat = [int(v) for v in block["split_feature"].split()]
            # LightGBM numerical splits are `x <= threshold -> left`;
            # _Tree tests `x < threshold` (xgboost semantics), so nudge
            # each threshold up one ULP at parse time.
            thresh = [float(np.nextafter(float(v), np.inf))
                      for v in block["threshold"].split()]
            lc = [int(v) for v in block["left_child"].split()]
            rc = [int(v) for v in block["right_child"].split()]
            dt = [int(v) for v in block.get(
                "decision_type", " ".join(["2"] * len(feat))).split()]
            if any(d & 1 for d in dt):
                # Bit 0 = categorical split: thresholds are
                # cat_boundaries indices, not comparable values.
                raise ValueError(
                    "model uses categorical splits; the native "
                    "evaluator handles numerical splits only — install "
                    "lightgbm for categorical models")
            if any((d >> 2) & 3 == 1 for d in dt):
                # Bits 2-3 = missing_type (0 None, 1 Zero, 2 NaN).  The
                # walk routes only NaN through the default branch; a
                # zero_as_missing model needs zeros routed there too —
                # reject at load rather than silently diverge from
                # lightgbm's output.
                raise ValueError(
                    "model uses zero-as-missing splits "
                    "(missing_type=Zero); the native evaluator routes "
                    "only NaN as missing — install lightgbm for this "
                    "model")
            n_internal = len(feat)
            # Flatten internal nodes then leaves into one array; child id
            # c >= 0 is internal node c, c < 0 is leaf ~c (= -(c)-1).
            def child(c):
                return c if c >= 0 else n_internal + (~c)
            value = [0.0] * n_internal + leaf_value
            trees.append(_Tree(
                feature=feat + [0] * num_leaves,
                threshold=thresh + [0.0] * num_leaves,
                left=[child(c) for c in lc] + [0] * num_leaves,
                right=[child(c) for c in rc] + [0] * num_leaves,
                # bit 2 of decision_type = default left
                default_left=[bool(d & 2) for d in dt] +
                             [False] * num_leaves,
                is_leaf=[False] * n_internal + [True] * num_leaves,
                value=value,
            ))

        for raw in text.splitlines():
            line = raw.strip()
            if line.startswith("Tree="):
                finish_block()
                block = {}
            elif line.startswith("end of trees"):
                finish_block()
                block = {}
            elif "=" in line:
                k, v = line.split("=", 1)
                block[k] = v
                if k == "objective":
                    objective = v
                    for part in v.split():
                        if part.startswith("num_class:"):
                            num_class = int(part.split(":")[1])
        finish_block()
        return cls(trees, num_class, objective)

    def predict(self, X: np.ndarray, raw_score: bool = False) -> np.ndarray:
        X = np.asarray(X, np.float64)
        out = np.zeros((X.shape[0], self.num_class))
        for i, tree in enumerate(self.trees):
            out[:, i % self.num_class] += tree.predict(X)
        if raw_score:
            return out[:, 0] if self.num_class == 1 else out
        if self.objective.startswith("binary"):
            return _sigmoid(out[:, 0])
        if self.objective.startswith(("multiclass", "softmax")):
            return _softmax(out)
        return out[:, 0] if self.num_class == 1 else out
