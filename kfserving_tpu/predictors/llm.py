"""GenerativeModel: the decoder-serving predictor.

Extends the predictor plugin boundary (reference pkg/apis/serving/
v1beta1/predictor.go:33-59 — the reference's frameworks are all
request/response; generation is this framework's TPU-native addition)
with KV-cache incremental decoding and continuous batching
(engine/generator.py).

Model directory layout (the `storage_uri` artifact):

    config.json          — required; see GenerativeConfig
    checkpoint.msgpack   — flax.serialization blob (optional: absent ->
                           random init, which tests/benchmarks use)

config.json schema:
    {
      "architecture": "decoder" | "decoder_tiny" | <registered>,
      "arch_kwargs": {...},
      "max_slots": 8,              # continuous-batching slot count
      "max_seq": 512,              # KV-cache capacity per slot
      "prefill_buckets": [64, 128, 256, 512],
      "max_new_tokens": 64,        # default generation budget
      "temperature": 0.0,          # default sampling temperature
      "tokenizer": "byte",         # "byte" | "hf:<name>"
      "block_size": 128,           # paged KV cache (optional): HBM
      "cache_blocks": 48,          #   scales with resident tokens,
                                   #   shared prompt prefixes share
                                   #   blocks; default pool = dense
                                   #   parity (max_slots*max_seq).
                                   #   NOTE: the TPU Pallas paged
                                   #   kernel requires block_size to
                                   #   be a multiple of 128 (lane
                                   #   width); other sizes serve
                                   #   correctly but fall back to the
                                   #   slower XLA gather path (logged
                                   #   once at load)
      "prefill_chunk_tokens": 512, # chunked prefill (paged only):
                                   #   a COLD prompt longer than this
                                   #   lands in block-aligned chunks
                                   #   interleaved with decode waves,
                                   #   so live streams stall one
                                   #   chunk's device time instead of
                                   #   the whole prompt's.  Size it so
                                   #   one chunk's device time ~ one
                                   #   decode wave (steps_per_call
                                   #   decode steps).  Must be a
                                   #   multiple of block_size.
      "host_tier_blocks": 256,     # host KV tier (paged only):
                                   #   capacity-evicted prefix blocks
                                   #   spill to a host-RAM mmap tier
                                   #   of this many blocks and fault
                                   #   back on the next turn instead
                                   #   of re-prefilling; 0/absent =
                                   #   off.  host_tier_dir overrides
                                   #   the spill-file location.
      "adaptive_depth": true,      # drop to depth-1 when every live
                                   #   stream finishes within the
                                   #   waves already in flight
      "speculative": {             # speculative decoding (optional;
        "tokens": 4,               #   default off, KFS_SPECDEC_TOKENS
                                   #   is the env twin): propose K
                                   #   tokens per live slot per wave,
                                   #   verify all K+1 positions in ONE
                                   #   target dispatch, commit the
                                   #   longest agreeing prefix —
                                   #   bit-exact with non-speculative
                                   #   decode for greedy AND seeded
                                   #   sampling.
        "draft": {                 #   optional draft model (absent ->
          "architecture": "...",   #   the zero-cost n-gram prompt-
          "arch_kwargs": {...},    #   lookup head proposes); loaded
          "model_dir": "...",      #   beside the target (model_dir
          "window": 32             #   defaults to the target's dir),
        }                          #   registered with the Residency-
      },                           #   Manager as "<name>:draft" and
                                   #   accounted in the HBM ledger.
      "mesh": {"tp": 2}            # within-replica tensor parallelism
    }

Request shapes (both V1 predict and the generate routes):
    {"instances": ["a prompt", {"prompt": "...", "max_tokens": 32,
                                "temperature": 0.7, "top_k": 40,
                                "top_p": 0.95, "seed": 7,
                                "stop": ["\n\n"], "logprobs": 3}]}
    {"text_input": "...", "parameters": {...}}   # v2 generate ext.
Response:
    {"predictions": [{"text": ..., "token_count": n,
                      "finish_reason": "eos"|"length"|"stop",
                      "logprobs": [...]}]}       # logprobs on request

Sampling runs on device (top-k/top-p mask-then-sample; seeded noise
keyed on (seed, position) so runs reproduce); stop sequences match
host-side in TEXT space on the decoded tail — the streaming path
holds back any suffix that could begin a stop sequence so clients
never see stop text, even split across K>1 token chunks.

The byte tokenizer (ids = UTF-8 bytes, BOS=256, EOS=257) keeps the
stack dependency-free and lossless for any input; "hf:<name>" resolves
a transformers tokenizer for real checkpoints.
"""

import json
import logging
import os
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from kfserving_tpu.engine.generator import GenerationEngine
from kfserving_tpu.engine.hbm import HBMManager
from kfserving_tpu.observability import metrics as obs_metrics
from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError, InvalidInput
from kfserving_tpu.storage import Storage

logger = logging.getLogger("kfserving_tpu.llm")

BOS_ID = 256
EOS_ID = 257

_warned_block_size = False


def _warn_paged_kernel_ineligible(block_size: int) -> None:
    """One warning per process: a block_size that isn't a 128-multiple
    silently loses the Pallas paged-kernel speedup on TPU (the XLA
    gather fallback serves correctly) — surface the config smell
    instead of hiding a perf cliff (ADVICE r5)."""
    global _warned_block_size
    if _warned_block_size:
        return
    _warned_block_size = True
    logger.warning(
        "block_size=%d is not a multiple of 128: the TPU Pallas paged-"
        "attention kernel is ineligible and decode uses the slower XLA "
        "gather path. Use a 128-multiple block_size to enable it.",
        block_size)


def _find_stop(text: str, stops: List[str]) -> int:
    """Earliest index of any stop sequence in `text`, or -1."""
    idx = -1
    for s in stops:
        i = text.find(s)
        if i >= 0 and (idx < 0 or i < idx):
            idx = i
    return idx


def _holdback_len(text: str, stops: List[str]) -> int:
    """Length of the longest suffix of `text` that is a proper prefix
    of some stop sequence — the streaming path must not emit those
    characters yet, or a stop split across chunks would leak to the
    client before the match completes."""
    hold = 0
    for s in stops:
        for length in range(min(len(s) - 1, len(text)), hold, -1):
            if text.endswith(s[:length]):
                hold = length
                break
    return hold


class IncrementalDecoder:
    """Streaming detokenizer: O(pending-window) work per token and
    emission-stable deltas.

    Slicing re-decoded full text by character index is wrong twice
    over: decode is not append-stable (a UTF-8 sequence split across
    tokens decodes to U+FFFD until its last byte arrives, then the
    SAME index holds a different character — the delta silently drops
    it), and re-decoding everything per token is O(n^2) on the event
    loop.  This decoder keeps a small window of not-yet-emitted
    tokens, re-decodes only that window, and releases text only when
    it can no longer change:

    - a trailing U+FFFD is held (it may be a partial multibyte
      sequence that completes next token; genuine garbage flushes at
      finish),
    - a suffix that is a proper prefix of a stop sequence is held
      (the holdback invariant: emitted text NEVER ends with a stop
      prefix, which also means stop matches only ever appear in the
      unemitted window),
    - the window compacts whenever everything in it has been emitted,
      so per-token work stays O(window), not O(generated-so-far).
    """

    def __init__(self, tokenizer, stops: List[str],
                 history: Optional[List[int]] = None):
        self.tok = tokenizer
        self.stops = stops
        self.max_stop = max((len(s) for s in stops), default=0)
        self._sent: List[str] = []
        self._pending: List[int] = []
        self._p_emitted = ""   # prefix of decode(_pending) already out
        self.degraded = False  # decode rewrote emitted text (exotic
        #                        tokenizer): deltas go best-effort and
        #                        the terminal text must come from a
        #                        full decode
        # Full token history, read only by the degraded path.  Callers
        # that already keep one (and append BEFORE each push) share it
        # via `history` so the fast path never stores a duplicate
        # O(generation) list next to the deliberately-bounded window.
        self._all: List[int] = [] if history is None else history
        self._owns_history = history is None
        self._final: Optional[str] = None  # degraded-stop truncation

    def push(self, token: int):
        """Feed one token; returns (delta, stopped).  `delta` is the
        newly releasable text (possibly empty); `stopped` means a stop
        sequence matched — delta then ends exactly before the match
        and the caller must stop the stream."""
        if self._owns_history:
            self._all.append(token)
        if self.degraded:
            return "", self._degraded_stop()
        self._pending.append(token)
        ptext = self.tok.decode(self._pending)
        if not ptext.startswith(self._p_emitted):
            # Decode rewrote already-emitted text: incremental deltas
            # are no longer trustworthy, but stop matching must NOT
            # silently vanish with them (ADVICE r5) — it falls back to
            # scanning the full re-decoded history each token.
            self.degraded = True
            if self.stops:
                logger.warning(
                    "tokenizer decode rewrote emitted text; stop-"
                    "sequence matching degraded to full re-decode "
                    "(deltas suspended, stops still honored)")
            return "", self._degraded_stop()
        rest = ptext[len(self._p_emitted):]
        if self.stops:
            idx = _find_stop(rest, self.stops)
            if idx >= 0:
                delta = rest[:idx]
                self._emit(delta, ptext)
                return delta, True
            hold = _holdback_len(rest, self.stops)
        else:
            hold = 0
        candidate = rest[:len(rest) - hold] if hold else rest
        while candidate.endswith("�"):
            candidate = candidate[:-1]
        self._emit(candidate, ptext)
        return candidate, False

    def _degraded_stop(self) -> bool:
        """Degraded-mode stop matching.  The common per-token check
        decodes only a bounded token tail (stops are short; the window
        gives each stop char 4x token slack), so a long degraded
        generation stays O(n·window), not O(n²).  Only a tail HIT pays
        one full re-decode — which both confirms the match against the
        authoritative text and yields the exact truncation index for
        `text()`."""
        if not self.stops:
            return False
        window = self.max_stop * 4 + 16
        tail = self.tok.decode(self._all[-window:])
        if _find_stop(tail, self.stops) < 0:
            return False
        full = self.tok.decode(self._all)
        idx = _find_stop(full, self.stops)
        if idx < 0:  # tail boundary artifact, not a real match
            return False
        self._final = full[:idx]
        return True

    def finish(self) -> str:
        """Flush everything still held (no stop matched); returns the
        final delta."""
        if self.degraded:
            return ""
        ptext = self.tok.decode(self._pending)
        if not ptext.startswith(self._p_emitted):
            self.degraded = True
            return ""
        delta = ptext[len(self._p_emitted):]
        self._emit(delta, ptext)
        return delta

    def text(self) -> str:
        """Text emitted so far (== the full truncated output after a
        stop, or the full output after finish()).  After a degraded-
        mode stop this is the truncated full decode; other degraded
        outcomes leave the terminal text to the caller's full decode."""
        if self._final is not None:
            return self._final
        return "".join(self._sent)

    # Tokens of context kept across window compaction: a window that
    # restarted at zero would re-decode its first token without its
    # neighbors, and piece-joining tokenizers (sentencepiece leading-
    # space, BPE cleanup) decode a boundary token differently alone.
    # Keeping a small suffix makes the boundary artifact identical in
    # p_emitted and in every later decode of the same window, so the
    # deltas cancel it out (the vLLM prefix-offset trick).
    _KEEP = 4

    def _emit(self, s: str, ptext: str):
        if s:
            self._sent.append(s)
            self._p_emitted += s
        # Compact: once the whole window is out, shrink it — this is
        # what keeps per-token cost O(window).
        if self._p_emitted == ptext and \
                len(self._pending) > self._KEEP:
            self._pending = self._pending[-self._KEEP:]
            self._p_emitted = self.tok.decode(self._pending)


def _lp_payload(req, tokens: List[int]) -> List[Dict[str, Any]]:
    """Per-token logprob records (aligned with content tokens)."""
    return [
        {"id": int(t), "logprob": c,
         "top": [{"id": i, "logprob": p} for i, p in top]}
        for t, c, top in zip(tokens, req.lp_chosen, req.lp_top)
    ]


class ByteTokenizer:
    """Lossless byte-level tokenizer: ids 0-255 are UTF-8 bytes, 256 is
    BOS, 257 is EOS.  vocab_size 258 — the decoder_tiny config rounds
    its embedding table up to a lane-friendly 384."""

    vocab_size = 258
    bos_id = BOS_ID
    eos_id = EOS_ID

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


def build_tokenizer(spec: str):
    if spec == "byte":
        return ByteTokenizer()
    if spec.startswith("hf:"):
        from transformers import AutoTokenizer  # baked-in dependency

        tok = AutoTokenizer.from_pretrained(spec[3:])
        if tok.eos_token_id is None:
            logger.warning(
                "tokenizer %s has no eos_token_id: generation will only "
                "stop at the token budget or a stop sequence", spec)

        class _HF:
            vocab_size = tok.vocab_size
            bos_id = tok.bos_token_id
            eos_id = tok.eos_token_id

            def encode(self, text, add_bos=True):
                # add_special_tokens=False: some tokenizers append EOS
                # (or wrap with template tokens) in plain encode(),
                # which would poison the prompt; BOS is added
                # explicitly and only when the tokenizer has one.
                ids = tok.encode(text, add_special_tokens=False)
                if add_bos and tok.bos_token_id is not None:
                    ids = [tok.bos_token_id] + ids
                return ids

            def decode(self, ids):
                return tok.decode(ids, skip_special_tokens=True)

        return _HF()
    raise InvalidInput(f"unknown tokenizer spec {spec!r}")


class GenerativeConfig:
    def __init__(self, architecture: str,
                 arch_kwargs: Optional[Dict] = None,
                 max_slots: int = 8, max_seq: int = 512,
                 prefill_buckets: Optional[List[int]] = None,
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 tokenizer: str = "byte",
                 steps_per_call: int = 1,
                 pipeline_depth: int = 2,
                 logprob_topk: int = 5,
                 block_size: Optional[int] = None,
                 cache_blocks: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 host_tier_blocks: Optional[int] = None,
                 host_tier_dir: Optional[str] = None,
                 adaptive_depth: bool = True,
                 speculative: Optional[Dict[str, Any]] = None,
                 mesh: Optional[Dict[str, int]] = None,
                 **_ignored):
        self.architecture = architecture
        self.arch_kwargs = arch_kwargs or {}
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.prefill_buckets = prefill_buckets
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.tokenizer = tokenizer
        # Decode steps per device dispatch: on high-RTT transports each
        # dispatch costs ~an RTT, so K steps per call multiplies
        # per-slot tokens/s by up to K (streaming granularity becomes
        # K tokens; at most K-1 wasted steps past an EOS).
        self.steps_per_call = int(steps_per_call)
        # Decode waves in flight (>=2 hides the dispatch RTT behind
        # device compute; 1 = strictly blocking, the A/B baseline).
        self.pipeline_depth = int(pipeline_depth)
        self.logprob_topk = int(logprob_topk)
        # Paged KV cache: block_size enables it (HBM scales with
        # resident tokens; identical prompt prefixes share blocks);
        # cache_blocks sizes the pool (default: dense-parity capacity).
        self.block_size = int(block_size) if block_size else None
        self.cache_blocks = (int(cache_blocks) if cache_blocks
                             else None)
        # Chunked prefill (paged only): cold prompts longer than this
        # land chunk-by-chunk between decode waves; adaptive depth
        # stops speculative waves that could only decode garbage.
        self.prefill_chunk_tokens = (int(prefill_chunk_tokens)
                                     if prefill_chunk_tokens else None)
        # Host KV tier (paged only): capacity-evicted prefix blocks
        # spill to a host-RAM mmap tier of this many blocks instead of
        # dropping; 0/None = off (KFS_KV_TIER_BLOCKS is the env twin).
        self.host_tier_blocks = (int(host_tier_blocks)
                                 if host_tier_blocks else None)
        self.host_tier_dir = host_tier_dir
        self.adaptive_depth = bool(adaptive_depth)
        # Speculative decoding: {"tokens": K, optional "draft":
        # {"architecture", "arch_kwargs", "model_dir", "window"}}.
        # None/absent defers to the engine's KFS_SPECDEC_TOKENS env
        # twin (n-gram proposer only); see the module docstring.
        self.speculative = dict(speculative) if speculative else None
        self.mesh = mesh or {}

    @classmethod
    def from_file(cls, path: str,
                  overrides: Optional[Dict[str, Any]] = None):
        with open(path) as f:
            data = json.load(f)
        if overrides:
            data.update(overrides)
        if "architecture" not in data:
            raise InvalidInput(
                f"{path} missing required key 'architecture'")
        return cls(**data)


class GenerativeModel(Model):
    """A served decoder with continuous batching and token streaming."""

    def __init__(self, name: str, model_dir: str,
                 config: Optional[GenerativeConfig] = None,
                 hbm: Optional[HBMManager] = None,
                 config_overrides: Optional[Dict[str, Any]] = None,
                 residency=None):
        super().__init__(name)
        self.model_dir = model_dir
        self.config = config
        self.hbm = hbm
        # Optional ResidencyManager: when present, a configured draft
        # model registers beside the target as "<name>:draft" so
        # `kfs models` shows it and the ledger accounts it.
        self.residency = residency
        self.config_overrides = dict(config_overrides or {})
        self.engine: Optional[GenerationEngine] = None
        self.tokenizer = None
        self._draft_handle = None
        # "mmap" | "checkpoint" | "init" once loaded.
        self.param_source: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    def load(self) -> bool:
        from kfserving_tpu import startup
        from kfserving_tpu.engine import param_cache
        from kfserving_tpu.models import create_model

        startup.mark("load_start")
        local = Storage.download(self.model_dir)
        startup.mark("download")
        cfg = self.config
        if cfg is None:
            cfg = GenerativeConfig.from_file(
                os.path.join(local, "config.json"),
                overrides=self.config_overrides)
            self.config = cfg
        self.tokenizer = build_tokenizer(cfg.tokenizer)
        if cfg.block_size is not None and cfg.block_size % 128 != 0:
            _warn_paged_kernel_ineligible(cfg.block_size)

        spec = create_model(cfg.architecture, **cfg.arch_kwargs)
        # mmap-first materialization (shared with JaxModel): a standby
        # successor maps the predecessor's persisted host params and
        # its activation cost collapses to the device transfer.
        variables, self.param_source = param_cache.load_or_materialize(
            cfg.architecture, cfg.arch_kwargs, spec, local)

        mesh = None
        if cfg.mesh:
            from kfserving_tpu.parallel import build_mesh, shard_params
            from kfserving_tpu.parallel.mesh import MeshConfig

            mesh_cfg = MeshConfig(**{k: int(v)
                                     for k, v in cfg.mesh.items()
                                     if k in ("dp", "tp", "sp")})
            if mesh_cfg.num_devices > 1:
                mesh = build_mesh(mesh_cfg)
                variables = {
                    **variables,
                    "params": shard_params(variables["params"], mesh),
                }

        speculative = None
        draft_meta = None
        if cfg.speculative and \
                int(cfg.speculative.get("tokens", 0)) > 0:
            speculative = {"tokens": int(cfg.speculative["tokens"])}
            draft_cfg = cfg.speculative.get("draft")
            if draft_cfg:
                # The draft is just a second model materialized
                # through the same mmap-first path, faulted in beside
                # the target — it shares the target's dir when no
                # model_dir of its own is given (self-draft and
                # co-packaged drafts).
                draft_kwargs = dict(draft_cfg.get("arch_kwargs")
                                    or {})
                draft_spec = create_model(draft_cfg["architecture"],
                                          **draft_kwargs)
                draft_dir = draft_cfg.get("model_dir")
                draft_local = (Storage.download(draft_dir)
                               if draft_dir else local)
                draft_vars, _ = param_cache.load_or_materialize(
                    draft_cfg["architecture"], draft_kwargs,
                    draft_spec, draft_local)
                window = int(draft_cfg.get("window", 0) or 0)
                speculative.update({
                    "draft_module": draft_spec.module,
                    "draft_variables": draft_vars,
                })
                if window:
                    speculative["draft_window"] = window
                draft_meta = (draft_spec.module, draft_vars, window)

        engine = GenerationEngine(
            spec.module, variables,
            max_slots=cfg.max_slots, max_seq=cfg.max_seq,
            prefill_buckets=cfg.prefill_buckets,
            eos_id=getattr(self.tokenizer, "eos_id", None),
            steps_per_call=cfg.steps_per_call,
            pipeline_depth=cfg.pipeline_depth,
            logprob_topk=cfg.logprob_topk,
            block_size=cfg.block_size,
            cache_blocks=cfg.cache_blocks,
            prefill_chunk_tokens=cfg.prefill_chunk_tokens,
            host_tier_blocks=cfg.host_tier_blocks,
            host_tier_dir=cfg.host_tier_dir,
            adaptive_depth=cfg.adaptive_depth,
            speculative=speculative,
            mesh=mesh, name=self.name)
        if self.hbm is not None:
            # Generation residency = params + the slot cache pool,
            # plus the draft model's params when speculation runs one
            # — the ledger accounts BOTH models of the pair.
            self.hbm.admit(self.name,
                           engine.param_bytes() + engine.cache_bytes()
                           + engine.draft_param_bytes())
        self.engine = engine
        if draft_meta is not None:
            from kfserving_tpu.engine.speculative import (
                DEFAULT_DRAFT_WINDOW,
                DraftModel,
            )

            module_d, vars_d, window = draft_meta
            self._draft_handle = DraftModel(
                f"{self.name}:draft", module_d, vars_d, engine,
                window=window or DEFAULT_DRAFT_WINDOW)
            if self.residency is not None:
                # Registers directly as resident (ready + engine set)
                # and PINNED: the manager must never evict the draft
                # out from under the serving target.
                self.residency.register(self._draft_handle.name,
                                        self._draft_handle)
        self.ready = True
        return True

    def unload(self) -> None:
        if self.engine is not None:
            self.engine.shutdown_nowait()
            self.engine = None
        if self._draft_handle is not None:
            if self.residency is not None:
                self.residency.deregister(self._draft_handle.name)
            # Unpin: a registration that outlives this unload must not
            # keep vetoing eviction.
            self._draft_handle.release()
            self._draft_handle = None
        if self.hbm is not None:
            self.hbm.release(self.name)
        self.ready = False

    async def close(self) -> None:
        if self.engine is not None:
            await self.engine.close()
            self.engine = None
        await super().close()

    # -- request parsing ---------------------------------------------------
    def _parse_instance(self, inst: Any) -> Dict[str, Any]:
        cfg = self.config
        if isinstance(inst, str):
            inst = {"prompt": inst}
        if not isinstance(inst, dict):
            raise InvalidInput(
                f"generate instance must be a string or object, got "
                f"{type(inst).__name__}")
        if "prompt" not in inst and "text_input" not in inst:
            raise InvalidInput(
                "generate instance needs 'prompt' (or 'text_input')")
        stop = inst.get("stop", [])
        if isinstance(stop, str):
            stop = [stop]
        if not (isinstance(stop, list)
                and all(isinstance(s, str) and s for s in stop)):
            raise InvalidInput(
                "stop must be a non-empty string or a list of them")
        seed = inst.get("seed")
        logprobs = inst.get("logprobs", 0)
        if logprobs is True:
            logprobs = 1
        return {
            "prompt": str(inst.get("prompt", inst.get("text_input"))),
            "max_tokens": int(inst.get("max_tokens",
                                       inst.get("max_new_tokens",
                                                cfg.max_new_tokens))),
            "temperature": float(inst.get("temperature",
                                          cfg.temperature)),
            "top_k": int(inst.get("top_k", 0)),
            "top_p": float(inst.get("top_p", 1.0)),
            "seed": None if seed is None else int(seed),
            "stop": stop,
            "logprobs": int(logprobs),
        }

    def _submit(self, parsed: Dict[str, Any]):
        ids = self.tokenizer.encode(parsed["prompt"])
        # Prompt-side token accounting (the "out" side increments per
        # emitted token in the engine's _emit).
        obs_metrics.llm_tokens_total().labels(direction="in").inc(
            len(ids))
        return self.engine.submit(
            ids, max_new_tokens=parsed["max_tokens"],
            temperature=parsed["temperature"],
            top_k=parsed["top_k"], top_p=parsed["top_p"],
            seed=parsed["seed"], logprobs=parsed["logprobs"])

    async def _run_one(self, parsed: Dict[str, Any]) -> Dict[str, Any]:
        req = self._submit(parsed)
        tokens: List[int] = []
        # tokens is appended BEFORE each push, so the decoder's
        # degraded path can share it instead of duplicating history.
        decoder = IncrementalDecoder(self.tokenizer, parsed["stop"],
                                     history=tokens)
        reason = "length"
        async for token, fin in self.engine.stream(req):
            if token is not None:
                tokens.append(token)
                _, stopped = decoder.push(token)
                if stopped:
                    # Stop sequences live in TEXT space (the tokenizer
                    # may split one across tokens); the match runs
                    # host-side on the decoded window and the engine
                    # slot is cancelled the moment it lands.
                    self.engine.cancel(req)
                    return self._result(req, decoder.text(), tokens,
                                        "stop", parsed)
            if fin is not None:
                reason = fin
        if reason == "timeout" and not tokens:
            # Budget died in the queue before a single token: a clean
            # 504 beats an empty 200.  With partial text, deliver it
            # with finish_reason "timeout" (the client paid for those
            # tokens; the engine freed the slot either way).
            from kfserving_tpu.reliability import DeadlineExceeded

            raise DeadlineExceeded("generation")
        decoder.finish()
        text = (self.tokenizer.decode(tokens) if decoder.degraded
                else decoder.text())
        return self._result(req, text, tokens, reason, parsed)

    def _result(self, req, text: str, tokens: List[int], reason: str,
                parsed: Dict[str, Any]) -> Dict[str, Any]:
        out = {"text": text, "token_count": len(tokens),
               "finish_reason": reason}
        if parsed["logprobs"] > 0:
            out["logprobs"] = _lp_payload(req, tokens)
        return out

    # -- serving entry points ----------------------------------------------
    async def predict(self, request: Any) -> Any:
        if self.predictor_host:
            return await super().predict(request)
        if self.engine is None:
            raise InferenceError(f"model {self.name} not loaded")
        import asyncio

        instances = v1.get_instances(request)
        if not instances:
            raise InvalidInput("generate needs at least one instance")
        parsed = [self._parse_instance(i) for i in instances]
        # Submit all instances at once: the engine's continuous batcher
        # shares decode steps across them (the request-level analogue of
        # the dynamic batcher).  return_exceptions: let every sibling
        # settle before surfacing a failure — an immediate propagate
        # would leave the others decoding unawaited to their full
        # budgets ("Task exception was never retrieved").
        results = await asyncio.gather(*[self._run_one(p)
                                         for p in parsed],
                                       return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return v1.make_response(list(results))

    async def generate(self, request: Any) -> Any:
        """Non-streaming :generate — v2 generate-extension shape in,
        single result out."""
        if self.engine is None:
            raise InferenceError(f"model {self.name} not loaded")
        parsed = self._parse_generate_body(request)
        result = await self._run_one(parsed)
        details = {"token_count": result["token_count"],
                   "finish_reason": result["finish_reason"]}
        if "logprobs" in result:
            details["logprobs"] = result["logprobs"]
        return {"model_name": self.name, "text_output": result["text"],
                "details": details}

    def _parse_generate_body(self, request: Any) -> Dict[str, Any]:
        if isinstance(request, dict) and (
                "text_input" in request or "prompt" in request):
            merged = dict(request)
            merged.update(request.get("parameters") or {})
            return self._parse_instance(merged)
        instances = v1.get_instances(request)
        if not instances:
            raise InvalidInput("generate needs a prompt")
        return self._parse_instance(instances[0])

    async def generate_stream(self, request: Any
                              ) -> AsyncIterator[Dict[str, Any]]:
        """Streaming :generate — an async iterator of per-token events:
        {"token": {"id", "text"}, ...} with a terminal event carrying
        finish_reason + the aggregate text.

        Validation and submission happen HERE, eagerly — before the
        caller commits response headers — so a bad prompt is a clean
        4xx, not a 200 followed by a dropped connection."""
        if self.engine is None:
            raise InferenceError(f"model {self.name} not loaded")
        parsed = self._parse_generate_body(request)
        req = self._submit(parsed)
        stops = parsed["stop"]
        want_lp = parsed["logprobs"] > 0

        finished = False

        async def events():
            nonlocal finished
            collected: List[int] = []
            # collected is appended BEFORE each push (shared history,
            # see IncrementalDecoder.__init__).
            decoder = IncrementalDecoder(self.tokenizer, stops,
                                         history=collected)

            def token_event(token, text_delta):
                event = {"token": {"id": int(token),
                                   "text": text_delta}}
                if want_lp and len(collected) <= len(req.lp_chosen):
                    i = len(collected) - 1
                    event["token"]["logprob"] = req.lp_chosen[i]
                    event["token"]["top_logprobs"] = [
                        {"id": t, "logprob": p}
                        for t, p in req.lp_top[i]]
                return event

            async for token, reason in self.engine.stream(req):
                if token is not None:
                    collected.append(token)
                    delta, stopped = decoder.push(token)
                    if stopped:
                        # Truncate at the match; never emit the stop
                        # text itself.
                        self.engine.cancel(req)
                        finished = True
                        event = token_event(token, delta)
                        event["finish_reason"] = "stop"
                        event["generated_text"] = decoder.text()
                        event["details"] = {
                            "token_count": len(collected)}
                        yield event
                        return
                    event = token_event(token, delta)
                else:
                    event = {}
                if reason is not None:
                    finished = True
                    # Flush anything held back: no stop matched.
                    tail = decoder.finish()
                    if tail:
                        tok = event.setdefault(
                            "token", {"id": None, "text": ""})
                        tok["text"] += tail
                    full = (self.tokenizer.decode(collected)
                            if decoder.degraded else decoder.text())
                    event["finish_reason"] = reason
                    event["generated_text"] = full
                    event["details"] = {"token_count": len(collected)}
                yield event

        def on_close():
            # Consumer abandoned the stream (client disconnect —
            # including before the first event was ever pulled): free
            # the decode slot instead of generating to the budget for
            # nobody.  No-op when the generation finished normally.
            if not finished:
                self.engine.cancel(req)

        from kfserving_tpu.streams import GuardedStream

        return GuardedStream(events(), on_close)

    def engine_stats(self) -> Dict[str, Any]:
        stats = dict(self.engine.stats()) if self.engine else {}
        if self.param_source is not None:
            stats["param_source"] = self.param_source
        return stats

    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        if self.config is not None:
            meta["platform"] = "jax-generate"
            meta["architecture"] = self.config.architecture
            meta["max_slots"] = self.config.max_slots
            meta["max_seq"] = self.config.max_seq
        return meta
