from kfserving_tpu.predictors.xgbserver.model import XGBoostModel  # noqa: F401
