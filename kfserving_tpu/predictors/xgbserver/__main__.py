"""`python -m kfserving_tpu.predictors.xgbserver` (reference
python/xgbserver/xgbserver/__main__.py arg surface)."""

import argparse
import logging

from kfserving_tpu.predictors.xgbserver.model import XGBoostModel
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model")
parser.add_argument("--model_dir", required=True)
parser.add_argument("--nthread", default=1, type=int)

if __name__ == "__main__":
    args, _ = parser.parse_known_args()
    model = XGBoostModel(args.model_name, args.model_dir, args.nthread)
    model.load()
    ModelServer(http_port=args.http_port,
                container_concurrency=args.container_concurrency
                ).start([model])
