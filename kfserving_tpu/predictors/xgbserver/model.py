"""xgboost predictor (reference python/xgbserver/xgbserver/model.py:
booster load from .bst, DMatrix predict).

Two execution paths:
- with the xgboost library installed: exact reference behavior
  (Booster + DMatrix) for any artifact format;
- without it: the native evaluator (predictors/trees.py) parses the
  documented JSON model format directly — .json artifacts serve with
  numpy only, so the predictor works in hermetic TPU images where
  xgboost isn't installed.
"""

from kfserving_tpu.predictors.tabular import TabularModel


class XGBoostModel(TabularModel):
    # .bst/.ubj are binary formats only the library reads; model JSON is
    # matched by name (model dirs routinely carry other JSON sidecars —
    # this repo's own config.json layout — that would trip the
    # exactly-one-artifact check).
    ARTIFACT_EXTENSIONS = (".bst", ".ubj", "model.json")

    def __init__(self, name: str, model_dir: str, nthread: int = 1):
        super().__init__(name, model_dir)
        self.nthread = nthread
        self._native = None

    def _load_artifact(self, path: str):
        try:
            import xgboost as xgb
        except ImportError:
            if not path.endswith(".json"):
                raise ImportError(
                    "xgboost is not installed and the native evaluator "
                    "reads only the JSON model format; save with "
                    "booster.save_model('model.json')")
            from kfserving_tpu.predictors.trees import XGBoostEnsemble

            self._native = XGBoostEnsemble.from_file(path)
            return self._native
        booster = xgb.Booster(params={"nthread": self.nthread},
                              model_file=path)
        return booster

    def _predict_batch(self, batch):
        if self._native is not None:
            return self._native.predict(batch)
        import xgboost as xgb

        dmatrix = xgb.DMatrix(batch, nthread=self.nthread)
        return self._model.predict(dmatrix)
