"""xgboost predictor (reference python/xgbserver/xgbserver/model.py:
booster load from .bst, DMatrix predict).  Import-gated: xgboost is not in
the hermetic image; the module loads and errors helpfully without it."""

from kfserving_tpu.predictors.tabular import TabularModel


class XGBoostModel(TabularModel):
    # .json deliberately excluded: model dirs routinely carry JSON sidecars
    # (this repo's own config.json layout) that would trip the exactly-one-
    # artifact check.
    ARTIFACT_EXTENSIONS = (".bst", ".ubj")

    def __init__(self, name: str, model_dir: str, nthread: int = 1):
        super().__init__(name, model_dir)
        self.nthread = nthread

    def _load_artifact(self, path: str):
        import xgboost as xgb

        booster = xgb.Booster(params={"nthread": self.nthread},
                              model_file=path)
        return booster

    def _predict_batch(self, batch):
        import xgboost as xgb

        dmatrix = xgb.DMatrix(batch, nthread=self.nthread)
        return self._model.predict(dmatrix)
