from kfserving_tpu.predictors.llm import (  # noqa: F401
    ByteTokenizer,
    GenerativeConfig,
    GenerativeModel,
)
