"""llmserver entrypoint: `python -m kfserving_tpu.predictors.llmserver`.

The generative predictor's standalone server — same CLI convention as
every per-framework server (`--model_name --model_dir --http_port`,
reference pkg/apis/serving/v1beta1/predictor_sklearn.go:77-96 builds
exactly these), serving :predict, :generate, and /generate_stream.
"""

import argparse
import logging

from kfserving_tpu.engine.compile_cache import enable as enable_compile_cache
from kfserving_tpu.predictors.llm import GenerativeModel
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model",
                    help="name under which the model is served")
parser.add_argument("--model_dir", required=True,
                    help="model artifact URI (config.json + optional "
                         "checkpoint.msgpack)")
parser.add_argument("--log_url", default=None,
                    help="CloudEvents sink for payload logging")
parser.add_argument("--log_mode", default="all",
                    choices=["all", "request", "response"])
parser.add_argument("--source_uri", default="",
                    help="CloudEvents source attribute")


def build_server(args) -> ModelServer:
    server = ModelServer(
        http_port=args.http_port,
        container_concurrency=getattr(args, "container_concurrency", 0),
        grpc_port=getattr(args, "grpc_port", None))
    if args.log_url:
        from kfserving_tpu.agent import RequestLogger

        request_logger = RequestLogger(
            args.log_url, source_uri=args.source_uri,
            log_mode=args.log_mode)
        request_logger.attach(server)
        server.services.append(request_logger)
    return server


if __name__ == "__main__":
    import os

    args, _ = parser.parse_known_args()
    enable_compile_cache()
    server = build_server(args)
    model = GenerativeModel(args.model_name, args.model_dir)
    if os.environ.get("KFS_STANDBY"):
        # Recycle fast-swap: load (device init + compile) deferred to
        # POST /standby/activate — see jaxserver/__main__.py.
        server.standby_model(lambda: (model.load(), model)[1])
        server.start([])
    else:
        model.load()
        server.start([model])
