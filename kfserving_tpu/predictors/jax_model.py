"""JaxModel: the TPU-native predictor.

Plays the role the reference delegates to pytorchserver/TFServing/Triton
(reference python/pytorchserver/pytorchserver/model.py loads a torch class
and predicts per-request with no batching): load a Flax model + params,
compile shape-bucketed executables, and serve V1/V2 predict through the
in-process dynamic batcher.

Model directory layout (the `storage_uri` artifact):

    config.json          — required; see JaxModelConfig
    checkpoint.msgpack   — flax.serialization byte blob of the variables
                           (optional: absent -> random init, which serving
                           tests and synthetic benchmarks use)

config.json schema (all optional except architecture):
    {
      "architecture": "resnet50" | "bert" | "vit_b16" | "mlp" | <registered>,
      "arch_kwargs": {...},            # forwarded to the registry factory
      "max_batch_size": 32,            # bucket ceiling (pow2 buckets)
      "max_latency_ms": 5.0,           # batcher flush deadline
      "seq_buckets": [64, 128, 256],   # seq-len buckets (token models)
      "input_dtype": "uint8"|"float32",# client payload dtype on the wire
      "scale": 0.00392156862,          # on-device input scaling (1/255)
      "output": "logits"|"argmax"|"topk",
      "topk": 5,
      "mesh": {"dp": 1, "tp": 1, "sp": 1}   # within-replica parallelism
    }

Design notes (TPU-first):
- uint8 on the wire + normalize on device: host->HBM bandwidth is the
  serving bottleneck; a float32 image batch is 4x the bytes of the same
  uint8 batch for zero accuracy gain before normalization.
- argmax/topk on device: the response rides back bytes-per-instance instead
  of the full logit row.
- multi-chip replicas are the same code path: params are placed with
  NamedShardings over the config mesh and the bucketed executables become
  SPMD programs (parallel/sharding.py rules).
"""

import json
import logging
import os
from typing import Any, Dict, List, Optional

import numpy as np

from kfserving_tpu.batching import DynamicBatcher
from kfserving_tpu.engine.buckets import BucketPolicy
from kfserving_tpu.engine.hbm import HBMManager
from kfserving_tpu.engine.jax_engine import JaxEngine
from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError, InvalidInput
from kfserving_tpu.protocol.v2 import InferRequest, make_response
from kfserving_tpu.storage import Storage

logger = logging.getLogger("kfserving_tpu.jaxserver")

DEFAULT_CONFIG_NAME = "config.json"
CHECKPOINT_NAME = "checkpoint.msgpack"


class JaxModelConfig:
    def __init__(self, architecture: str, arch_kwargs: Optional[Dict] = None,
                 max_batch_size: int = 32, max_latency_ms: float = 5.0,
                 batch_buckets: Optional[List[int]] = None,
                 seq_buckets: Optional[List[int]] = None,
                 input_dtype: str = "float32", scale: Optional[float] = None,
                 output: str = "logits", topk: int = 5,
                 mesh: Optional[Dict[str, int]] = None,
                 warmup: bool = True, pipeline_depth: int = 2,
                 **_ignored):
        self.architecture = architecture
        self.arch_kwargs = arch_kwargs or {}
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        # Explicit batch buckets bound compile count (each bucket is one
        # XLA program); default pow2 ladder up to max_batch_size.
        self.batch_buckets = batch_buckets
        self.seq_buckets = seq_buckets
        self.input_dtype = input_dtype
        self.scale = scale
        self.output = output
        self.topk = topk
        self.mesh = mesh or {}
        self.warmup = warmup
        self.pipeline_depth = pipeline_depth

    @classmethod
    def from_file(cls, path: str,
                  overrides: Optional[Dict[str, Any]] = None
                  ) -> "JaxModelConfig":
        """Load config.json, with deployment-time overrides layered on
        top (the control plane's ParallelismSpec injects `mesh` here —
        the artifact stays mesh-agnostic, placement is a spec concern)."""
        with open(path) as f:
            data = json.load(f)
        if overrides:
            data.update(overrides)
        if "architecture" not in data:
            raise InvalidInput(f"{path} missing required key 'architecture'")
        return cls(**data)


class JaxModel(Model):
    """A served JAX/Flax model with bucketed batched execution."""

    def __init__(self, name: str, model_dir: str,
                 config: Optional[JaxModelConfig] = None,
                 hbm: Optional[HBMManager] = None,
                 config_overrides: Optional[Dict[str, Any]] = None,
                 residency=None):
        super().__init__(name)
        self.model_dir = model_dir
        self.config = config
        self.hbm = hbm
        # ResidencyManager (engine/residency.py): when set, this model
        # is demand-paged — register() makes it addressable with no
        # device memory, predict faults it into HBM transparently, and
        # eviction offloads (host mmap params stay) instead of
        # unloading.
        self.residency = residency
        self.config_overrides = dict(config_overrides or {})
        self.engine: Optional[JaxEngine] = None
        self.batcher: Optional[DynamicBatcher] = None
        # Cached admission estimate: a cold fault whose admission finds
        # every victim busy retries load() every ~20 ms (residency
        # admit-wait) — the eval_shape trace must not be re-paid per
        # attempt.
        self._admit_nbytes: Optional[int] = None
        self._local_dir: Optional[str] = None
        # How this model's params were materialized at load: "mmap"
        # (param-cache hit), "checkpoint", or "init".
        self.param_source: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    def register(self) -> bool:
        """Declarative registration (residency mode): host-side prep
        only — artifact download + config parse, no device memory, no
        compile.  The model becomes `ready` (addressable; the predict
        path cold-faults the engine in on first use).  Registration of
        N models is O(N) file reads, not N compile grids."""
        from kfserving_tpu import startup

        startup.mark("load_start")
        self._local_dir = Storage.download(self.model_dir)
        startup.mark("download")
        if self.config is None:
            self.config = JaxModelConfig.from_file(
                os.path.join(self._local_dir, DEFAULT_CONFIG_NAME),
                overrides=self.config_overrides)
        if self.residency is not None:
            self.residency.register(self.name, self)
        self.ready = True
        return True

    def load(self) -> bool:
        from kfserving_tpu.models import create_model, init_params

        from kfserving_tpu import startup

        startup.mark("load_start")
        if self.residency is not None and self._local_dir:
            # Residency-managed cold fault: register() already pulled
            # the artifact, and the admit-wait loop retries load()
            # every ~20 ms — re-downloading a REMOTE storage_uri into
            # a fresh temp dir per retry would turn a busy-victim wait
            # into a download storm.
            pass
        else:
            self._local_dir = Storage.download(self.model_dir)
        startup.mark("download")
        cfg = self.config
        if cfg is None:
            cfg = JaxModelConfig.from_file(
                os.path.join(self._local_dir, DEFAULT_CONFIG_NAME),
                overrides=self.config_overrides)
            self.config = cfg

        spec = create_model(cfg.architecture, **cfg.arch_kwargs)

        # Reload is transactional: the new engine/batcher are built aside
        # and swapped in only on success.  During a reload, BOTH
        # generations are physically resident until the swap, so the new
        # one is admitted under a staging key alongside the old entry
        # (zero-downtime path).  When HBM has no headroom for both, fall
        # back to stop-the-world: close the old generation first, then
        # admit and build (downtime, but never device overcommit).
        old_engine = self.engine
        staging_key = f"{self.name}!staging"  # '!' excluded from names
        zero_downtime = True
        if self.hbm is not None:
            import jax

            from kfserving_tpu.engine.hbm import InsufficientHBM

            if self._admit_nbytes is None:
                abstract = jax.eval_shape(
                    lambda: init_params(spec, seed=0))
                self._admit_nbytes = sum(
                    int(np.prod(leaf.shape)) *
                    np.dtype(leaf.dtype).itemsize
                    for leaf in jax.tree.leaves(abstract))
            nbytes = self._admit_nbytes
            if old_engine is None:
                self.hbm.admit(self.name, nbytes)
            else:
                try:
                    # evict=False: staging must never evict live models
                    # (including this model's own serving generation) —
                    # no headroom means the stop-the-world path below.
                    self.hbm.admit(staging_key, nbytes, evict=False)
                except InsufficientHBM:
                    zero_downtime = False
                    self.ready = False
                    self.engine, self.batcher = None, None
                    old_engine.close()
                    old_engine = None
                    self.hbm.release(self.name)
                    self.hbm.admit(self.name, nbytes)
        elif old_engine is None:
            nbytes = None

        try:
            engine, batcher = self._build_engine(spec, cfg)
        except Exception:
            if self.hbm is not None:
                if old_engine is not None:
                    self.hbm.release(staging_key)  # old entry untouched
                else:
                    self.hbm.release(self.name)
            raise
        self.engine, self.batcher = engine, batcher
        self.ready = True
        if old_engine is not None:
            old_engine.close()  # quiesces in-flight work, frees old HBM
            if self.hbm is not None and zero_downtime:
                # Atomic commit: staging entry becomes the model's entry
                # under the manager lock (no release/re-admit window a
                # concurrent admit could claim).
                self.hbm.commit(staging_key, self.name,
                                engine.param_bytes())
        if self.residency is not None:
            # Idempotent for the cold-fault path (the manager already
            # holds this model's record); a direct eager load joins the
            # managed set as resident.
            self.residency.register(self.name, self)
        return True

    def _build_engine(self, spec, cfg):
        import jax.numpy as jnp

        from kfserving_tpu.engine import param_cache
        from kfserving_tpu.models import apply_fn_for
        from kfserving_tpu.parallel import build_mesh, shard_params
        from kfserving_tpu.parallel.mesh import MeshConfig

        from kfserving_tpu import startup

        # Kept for subclasses that need the raw logits path (explainers
        # differentiate through base_apply, not the serving output mode).
        self._spec = spec
        # mmap-first param materialization: a recycle successor (or a
        # cheap canary spawn) maps the predecessor's persisted host
        # bytes instead of re-running init + checkpoint restore — the
        # 8-18 s init_params residual of the r5 SOAK becomes page-cache
        # reads feeding the device transfer.
        variables, param_source = param_cache.load_or_materialize(
            cfg.architecture, cfg.arch_kwargs, spec, self._local_dir,
            checkpoint_name=CHECKPOINT_NAME)
        self.param_source = param_source

        mesh_cfg = MeshConfig(**{k: int(v) for k, v in cfg.mesh.items()
                                 if k in ("dp", "tp", "sp")})
        mesh = None
        if mesh_cfg.num_devices > 1:
            mesh = build_mesh(mesh_cfg)
            with mesh:
                variables = {
                    **variables,
                    "params": shard_params(variables["params"], mesh),
                }
        if mesh is not None and mesh_cfg.sp > 1:
            # Sequence parallelism: rebuild the serving module with ring
            # attention closed over the mesh (models/bert.py attn_fn
            # hook; parameters are attention-impl-independent, so the
            # restored checkpoint applies unchanged).  Architectures
            # without a pluggable attention can't shard the sequence
            # axis — fail at load, not silently serve unsharded.
            from kfserving_tpu.models import create_model
            from kfserving_tpu.parallel.ring_attention import (
                ring_attention_sharded,
            )

            try:
                spec = create_model(
                    cfg.architecture,
                    attn_fn=ring_attention_sharded(mesh),
                    **cfg.arch_kwargs)
            except TypeError as e:
                raise InvalidInput(
                    f"architecture {cfg.architecture!r} does not "
                    f"support sequence parallelism (no pluggable "
                    f"attention hook): {e}")
            self._spec = spec

        base_apply = apply_fn_for(spec)
        self._base_apply = base_apply
        scale = cfg.scale
        output_mode, topk = cfg.output, cfg.topk

        def serve_fn(v, batch):
            x = batch
            if not isinstance(x, dict) and scale is not None:
                x = x.astype(jnp.bfloat16) * scale
            out = base_apply(v, x)
            if output_mode == "argmax":
                return jnp.argmax(out, axis=-1).astype(jnp.int32)
            if output_mode == "topk":
                import jax

                vals, idx = jax.lax.top_k(out, topk)
                return {"values": vals.astype(jnp.float32),
                        "indices": idx.astype(jnp.int32)}
            return out

        seq_buckets = (BucketPolicy(cfg.seq_buckets)
                       if cfg.seq_buckets else None)
        residency_managed = self.residency is not None
        engine = JaxEngine(
            serve_fn, variables,
            batch_buckets=(BucketPolicy(cfg.batch_buckets)
                           if cfg.batch_buckets
                           else BucketPolicy.pow2(cfg.max_batch_size)),
            seq_buckets=seq_buckets,
            pipeline_depth=cfg.pipeline_depth,
            param_source=param_source)
        if residency_managed and engine.offloadable:
            # Pin the params in HBM explicitly (one device_put of the
            # mmap views) so residency accounting matches physical
            # placement; the host tree stays as the restore source for
            # every later evict -> fault-in cycle.
            engine.restore()
        try:
            if cfg.warmup:
                example = self._example_instance(spec)
                # Recycle successors trim the grid: the predecessor's
                # persistent compile cache makes on-demand bucket
                # loads cheap, and a fast successor shortens the
                # contention window that drives the swap's p99.
                engine.warmup(example, minimal=(
                    os.environ.get("KFS_MINIMAL_WARMUP", "")
                    not in ("", "0", "false")))
                startup.mark("warmup")
        except Exception:
            engine.close()
            raise

        batcher = DynamicBatcher(
            self._batch_handler,
            # Chunk limit = the largest compiled bucket, so a flush never
            # exceeds what the engine can execute in one call.
            max_batch_size=(max(cfg.batch_buckets) if cfg.batch_buckets
                            else cfg.max_batch_size),
            max_latency_ms=cfg.max_latency_ms,
            key_fn=self._bucket_key if seq_buckets else None,
            # One more than the engine's worker threads so a fresh batch
            # is always staged when a thread frees (the batcher defers
            # flushes past this — small batches coalesce while the
            # engine is busy instead of queueing tiny executions).
            max_inflight=cfg.pipeline_depth + 1,
            # Bucket-aligned flushing: executed batches land exactly on
            # the engine's compiled shapes, so pad waste comes only from
            # drain-out tails (VERDICT r2: 62% of ResNet batch slots were
            # padding with misaligned flushes).
            buckets=engine.batch_buckets.buckets)
        return engine, batcher

    def _example_instance(self, spec):
        cfg = self.config
        if isinstance(spec.example, dict):
            return {k: np.asarray(v)[0] for k, v in spec.example.items()}
        ex = np.asarray(spec.example)[0]
        if cfg.input_dtype == "uint8":
            return np.zeros(ex.shape, np.uint8)
        return ex.astype(cfg.input_dtype)

    def unload(self) -> None:
        if self.residency is not None:
            self.residency.deregister(self.name)
        if self.engine is not None:
            self.engine.close()
            self.engine = None
        if self.hbm is not None:
            self.hbm.release(self.name)
        self.batcher = None
        self.ready = False

    # -- residency hooks (engine/residency.py contract) --------------------
    @property
    def offloadable(self) -> bool:
        """Can this model leave HBM without losing its warm state?
        True once the engine keeps a host-side (mmap-backed) restore
        source — mesh-sharded models return False and are never
        eviction victims."""
        return self.engine is not None and self.engine.offloadable

    def offload(self) -> None:
        """Eviction body: drop device params, keep everything else
        (engine shell, compiled executables, batcher, host mmap
        params).  The model stays `ready` — the next predict faults it
        back in, in milliseconds."""
        if self.engine is not None:
            self.engine.offload()

    def demote(self) -> None:
        """Eviction body for models without a host restore source
        (param cache disabled, mesh-sharded params): drop the engine
        entirely.  The model stays registered and addressable; its
        next predict cold-faults a fresh build."""
        if self.engine is not None:
            self.engine.close()
            self.engine = None
        self.batcher = None

    def fault_in(self) -> None:
        """Warm fault body (blocking; residency executor): re-place
        the host params on device."""
        if self.engine is None:
            raise InferenceError(
                f"model {self.name} has no engine to fault in")
        self.engine.restore()

    def host_bytes(self) -> int:
        """HBM bytes a fault-in of this model will claim."""
        if self.engine is None:
            return 0
        return self.engine.host_param_bytes() or self.engine.param_bytes()

    @property
    def wire_dtype(self):
        """Dtype hint for the server's native V1 JSON parser: uint8
        models take integer image bodies straight to uint8 on the wire
        (tensorjson fast path; ROOFLINE.md: V1 JSON intake is the
        ~400 req/s wall)."""
        if self.config is not None and self.config.input_dtype == "uint8":
            return "u1"
        return None

    # -- inference ---------------------------------------------------------
    def _bucket_key(self, instance: Any):
        """Seq-bucket key: instances whose (padded) seq length lands in
        different buckets never share a batch."""
        arr = (next(iter(instance.values())) if isinstance(instance, dict)
               else instance)
        arr = np.asarray(arr)
        n = arr.shape[0] if arr.ndim else 1
        bucket = self.engine.seq_buckets.fit(n)
        if bucket is None:
            raise InvalidInput(
                f"sequence length {n} exceeds the largest bucket "
                f"{self.engine.seq_buckets.max}")
        return bucket

    async def _batch_handler(self, instances: List[Any], key=None) -> List[Any]:
        first = instances[0]
        if isinstance(first, dict):
            keys = list(first.keys())
            batch = {}
            for k in keys:
                rows = [np.asarray(inst[k]) for inst in instances]
                if key is not None:  # pad rows to the shared seq bucket
                    rows = [self._pad_seq(r, key) for r in rows]
                batch[k] = np.stack(rows)
            if "attention_mask" in batch:
                self._check_prefix_mask(batch["attention_mask"])
        else:
            rows = [np.asarray(inst) for inst in instances]
            lengths = [r.shape[0] if r.ndim else 1 for r in rows]
            if key is not None:
                rows = [self._pad_seq(r, key) for r in rows]
            batch = np.stack(rows)
            if self.config.input_dtype == "uint8":
                batch = batch.astype(np.uint8)
            if (isinstance(self._spec.example, dict)
                    and "attention_mask" in self._spec.example):
                # Canonicalize bare token rows to the dict signature the
                # model (and warmup) uses, with a synthesized padding
                # mask.  Two birds: seq-padding is no longer attended
                # to, and array requests share the warmed executable
                # instead of compiling a second signature at serve time
                # (~25s/shape on a tunneled chip = p99 in the seconds).
                primary = next(iter(self._spec.example))
                mask = np.zeros(batch.shape[:2], np.int32)
                for i, n in enumerate(lengths):
                    mask[i, :n] = 1
                batch = {primary: batch, "attention_mask": mask}
        out = await self.engine.predict(batch)
        return self._scatter(out, len(instances))

    def _check_prefix_mask(self, mask: np.ndarray) -> None:
        """Models running with prefix_padding (the default for the BERT
        family) interpret attention_mask as suffix padding and serve it
        through the padding-aware flash kernel.  A non-suffix mask
        (e.g. left padding) would be SILENTLY wrong on that path, so
        reject it loudly here on the host — callers with arbitrary mask
        patterns set arch_kwargs.prefix_padding=false (XLA path)."""
        if not self.config.architecture.startswith("bert"):
            return  # other archs don't derive kv_lengths from the mask
        if not self.config.arch_kwargs.get("prefix_padding", True):
            return
        m = np.asarray(mask)
        if m.ndim != 2:
            return
        # suffix form == row values never increase (1s then 0s)
        if np.any(np.diff(m.astype(np.int8), axis=1) > 0):
            raise InvalidInput(
                "attention_mask is not suffix padding (1s then 0s); "
                "this model serves masks as sequence lengths "
                "(prefix_padding). Set arch_kwargs.prefix_padding=false "
                "in the model config to serve arbitrary mask patterns.")

    @staticmethod
    def _pad_seq(row: np.ndarray, bucket: int) -> np.ndarray:
        if row.shape[0] == bucket:
            return row
        pad = [(0, bucket - row.shape[0])] + [(0, 0)] * (row.ndim - 1)
        return np.pad(row, pad)

    @staticmethod
    def _scatter(out: Any, n: int) -> List[Any]:
        if isinstance(out, dict):
            parts = {k: np.asarray(v) for k, v in out.items()}
            return [{k: v[i] for k, v in parts.items()} for i in range(n)]
        arr = np.asarray(out)
        return [arr[i] for i in range(n)]

    async def predict(self, request: Any) -> Any:
        if self.predictor_host:
            return await super().predict(request)
        if self.residency is not None:
            # Demand-paged residency gate: count this request as
            # in-flight (never evict a model with queued work), fault
            # the model into HBM if needed (single-flight, transparent
            # to the caller), and touch the LRU ledger so victims
            # reflect use order.
            async with self.residency.serving(self.name):
                return await self._predict_resident(request)
        return await self._predict_resident(request)

    async def _predict_resident(self, request: Any) -> Any:
        if self.batcher is None:
            raise InferenceError(f"model {self.name} not loaded")
        if isinstance(request, InferRequest) or (
                isinstance(request, dict)
                and isinstance(request.get("inputs"), list)
                and request["inputs"]
                and isinstance(request["inputs"][0], dict)
                and "datatype" in request["inputs"][0]):
            return await self._predict_v2(request)
        instances = v1.get_instances(request)
        result = await self.batcher.submit(instances)
        preds = result.predictions
        # Uniform float32 predictions stay an ndarray so the server's
        # native codec serializes them in one pass (protocol/native.py).
        if preds and isinstance(preds[0], np.ndarray) \
                and preds[0].dtype == np.float32 \
                and all(p.shape == preds[0].shape for p in preds[1:]):
            return v1.make_response(np.stack(preds))
        return v1.make_response([_tolist(p) for p in preds])

    async def _predict_v2(self, request: Any) -> Dict[str, Any]:
        req = (request if isinstance(request, InferRequest)
               else InferRequest.from_dict(request))
        named = req.named_numpy()
        if len(named) == 1:
            batch = next(iter(named.values()))
            instances = [batch[i] for i in range(batch.shape[0])]
        else:
            n = next(iter(named.values())).shape[0]
            instances = [{k: v[i] for k, v in named.items()}
                         for i in range(n)]
        result = await self.batcher.submit(instances)
        preds = result.predictions
        if preds and isinstance(preds[0], dict):
            outputs = {k: np.stack([p[k] for p in preds])
                       for k in preds[0]}
        else:
            outputs = {"output_0": np.stack(preds)}
        return make_response(self.name, outputs, id=req.id)

    # -- metadata ----------------------------------------------------------
    def metadata(self) -> Dict[str, Any]:
        meta = super().metadata()
        if self.engine is not None and self.config is not None:
            meta["platform"] = "jax"
            meta["architecture"] = self.config.architecture
            meta["batch_buckets"] = list(self.engine.batch_buckets.buckets)
            if self.engine.seq_buckets:
                meta["seq_buckets"] = list(self.engine.seq_buckets.buckets)
            meta.update(self._signature_metadata())
        return meta

    def _signature_metadata(self) -> Dict[str, Any]:
        """V2 model-metadata inputs/outputs (required_api.md Model
        Metadata): shapes/dtypes from jax.eval_shape of the serving
        function — abstract evaluation, no device work.  Batch dim
        reports -1 (dynamic; buckets are an engine detail)."""
        try:
            import jax

            from kfserving_tpu.protocol.v2 import datatype_of

            spec = self._spec
            example = spec.example
            if isinstance(example, dict):
                example = {k: np.asarray(v) for k, v in example.items()}
                inputs = [{"name": k,
                           "datatype": datatype_of(np.asarray(v)),
                           "shape": [-1] + list(np.asarray(v).shape[1:])}
                          for k, v in example.items()]
            else:
                example = np.asarray(example)
                if self.config.input_dtype == "uint8":
                    example = example.astype(np.uint8)
                inputs = [{"name": "input_0",
                           "datatype": datatype_of(example),
                           "shape": [-1] + list(example.shape[1:])}]
            out = jax.eval_shape(
                lambda v, x: self.engine._jitted.__wrapped__(v, x)
                if hasattr(self.engine._jitted, "__wrapped__")
                else self.engine._jitted(v, x),
                self.engine.params, example)
            leaves = (out.items() if isinstance(out, dict)
                      else [("output_0", out)])
            outputs = [{"name": k,
                        "datatype": datatype_of(
                            np.empty(0, dtype=leaf.dtype)),
                        "shape": [-1] + list(leaf.shape[1:])}
                       for k, leaf in leaves]
            return {"inputs": inputs, "outputs": outputs}
        except Exception:  # metadata is best-effort, never fatal
            logger.debug("signature metadata unavailable", exc_info=True)
            return {}

    def engine_stats(self) -> Dict[str, Any]:
        stats = dict(self.engine.stats()) if self.engine else {}
        if self.batcher:
            stats.update({
                "batches_flushed": self.batcher.batches_flushed,
                "instances_batched": self.batcher.instances_batched,
            })
            if self.batcher.queue_age_ms:
                # Per-bucket flush-time queue age — exported as labeled
                # series on /metrics (starvation diagnostic).
                stats["bucket_queue_age_max_ms"] = {
                    str(k): v["max"]
                    for k, v in self.batcher.queue_age_ms.items()}
        return stats


def _tolist(x: Any) -> Any:
    if isinstance(x, dict):
        return {k: _tolist(v) for k, v in x.items()}
    arr = np.asarray(x)
    return arr.item() if arr.ndim == 0 else arr.tolist()
