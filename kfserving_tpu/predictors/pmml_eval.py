"""Native PMML evaluator: TreeModel + RegressionModel, stdlib-only.

The reference pmmlserver evaluates with pypmml — a JVM bridge
(reference python/pmmlserver/pmmlserver/model.py).  That's a heavyweight
optional dependency; PMML itself is just XML, and the two model kinds
the reference's examples use (decision trees, regressions) evaluate in
a few dozen lines.  This keeps the pmml predictor serving in hermetic
images, with pypmml as the optional exact-parity path.

Supported: SimplePredicate (all six operators), CompoundPredicate
(and/or), True/False predicates, nested Nodes with scores,
ScoreDistribution probabilities, RegressionTable with NumericPredictors.
Missing features raise at load, not silently at predict.
"""

import xml.etree.ElementTree as ET
from typing import Any, Dict, List, Optional

import numpy as np


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _children(el, name: str):
    return [c for c in el if _local(c.tag) == name]


_OPS = {
    "equal": lambda x, v: x == v,
    "notEqual": lambda x, v: x != v,
    "lessThan": lambda x, v: x < v,
    "lessOrEqual": lambda x, v: x <= v,
    "greaterThan": lambda x, v: x > v,
    "greaterOrEqual": lambda x, v: x >= v,
}


class _Predicate:
    def __init__(self, el, field_index: Dict[str, int]):
        self.kind = _local(el.tag)
        if self.kind == "SimplePredicate":
            field = el.get("field")
            if field not in field_index:
                raise ValueError(f"predicate references unknown field "
                                 f"{field!r}")
            self.col = field_index[field]
            op = el.get("operator")
            if op not in _OPS:
                raise ValueError(f"unsupported operator {op!r}")
            self.op = _OPS[op]
            self.value = float(el.get("value"))
        elif self.kind == "CompoundPredicate":
            self.bool_op = el.get("booleanOperator")
            if self.bool_op not in ("and", "or"):
                raise ValueError(
                    f"unsupported booleanOperator {self.bool_op!r}")
            self.parts = [_Predicate(c, field_index) for c in el
                          if _local(c.tag).endswith("Predicate")
                          or _local(c.tag) in ("True", "False")]
        elif self.kind not in ("True", "False"):
            raise ValueError(f"unsupported predicate {self.kind!r}")

    def test(self, row: np.ndarray) -> bool:
        if self.kind == "True":
            return True
        if self.kind == "False":
            return False
        if self.kind == "SimplePredicate":
            return bool(self.op(row[self.col], self.value))
        results = (p.test(row) for p in self.parts)
        return all(results) if self.bool_op == "and" else any(results)


class _Node:
    def __init__(self, el, field_index: Dict[str, int]):
        self.score: Optional[str] = el.get("score")
        pred_el = next(
            (c for c in el if _local(c.tag) in
             ("SimplePredicate", "CompoundPredicate", "True", "False")),
            None)
        # A root node without a predicate is implicitly True.
        self.predicate = (_Predicate(pred_el, field_index)
                          if pred_el is not None else None)
        self.children = [_Node(c, field_index) for c in _children(el, "Node")]
        self.distribution = {
            c.get("value"): float(c.get("recordCount"))
            for c in _children(el, "ScoreDistribution")
        }

    def evaluate(self, row: np.ndarray):
        for child in self.children:
            if child.predicate is None or child.predicate.test(row):
                return child.evaluate(row)
        return self


class PMMLModel:
    """A parsed PMML TreeModel or RegressionModel."""

    def __init__(self, path: str):
        root = ET.parse(path).getroot()
        dd = next(iter(_children(root, "DataDictionary")), None)
        if dd is None:
            raise ValueError("PMML file missing DataDictionary")
        self.fields: List[str] = []
        self.target: Optional[str] = None
        model_el = None
        for kind in ("TreeModel", "RegressionModel"):
            found = _children(root, kind)
            if found:
                model_el = found[0]
                self.kind = kind
                break
        else:
            kinds = sorted({_local(c.tag) for c in root})
            raise ValueError(
                f"no supported model in PMML (found {kinds}; native "
                f"evaluator handles TreeModel/RegressionModel — install "
                f"pypmml for others)")
        # Active fields in MiningSchema order define the input columns
        # (the reference passes a positional row list, model.py).
        schema = next(iter(_children(model_el, "MiningSchema")))
        for mf in _children(schema, "MiningField"):
            if mf.get("usageType") in ("target", "predicted"):
                self.target = mf.get("name")
            else:
                self.fields.append(mf.get("name"))
        index = {f: i for i, f in enumerate(self.fields)}
        self.function = model_el.get("functionName", "classification")

        if self.kind == "TreeModel":
            self.root = _Node(
                next(iter(_children(model_el, "Node"))), index)
        else:
            self.normalization = model_el.get(
                "normalizationMethod", "none")
            if self.normalization not in ("none", "softmax", "logit"):
                raise ValueError(
                    f"unsupported normalizationMethod "
                    f"{self.normalization!r} (native evaluator handles "
                    f"none/softmax/logit — install pypmml for others)")
            table_els = _children(model_el, "RegressionTable")
            self.tables = []
            for t in table_els:
                coeffs = np.zeros(len(self.fields))
                for p in _children(t, "NumericPredictor"):
                    coeffs[index[p.get("name")]] = float(
                        p.get("coefficient"))
                self.tables.append((t.get("targetCategory"),
                                    float(t.get("intercept", 0.0)),
                                    coeffs))

    def predict_row(self, row: np.ndarray) -> Dict[str, Any]:
        """One row -> output dict (mirrors pypmml's predict().values()
        shape: predicted value first, then class probabilities)."""
        if self.kind == "TreeModel":
            leaf = self.root.evaluate(row)
            out: Dict[str, Any] = {"predicted": leaf.score}
            total = sum(leaf.distribution.values())
            if total > 0:
                for cls, count in leaf.distribution.items():
                    out[f"probability_{cls}"] = count / total
            return out
        scores = [(cat, intercept + float(row @ coeffs))
                  for cat, intercept, coeffs in self.tables]
        if self.function == "regression" or len(scores) == 1:
            return {"predicted": scores[0][1]}
        z = np.array([s for _, s in scores])
        if self.normalization == "softmax":
            p = np.exp(z - z.max())
            p /= p.sum()
        elif self.normalization == "logit" and len(scores) == 2:
            p1 = 1.0 / (1.0 + np.exp(-z[0]))
            p = np.array([p1, 1.0 - p1])
        else:  # "none": raw scores rank categories, no probabilities
            p = None
        best = int(np.argmax(z if p is None else p))
        out = {"predicted": scores[best][0]}
        if p is not None:
            for (cat, _), prob in zip(scores, p):
                out[f"probability_{cat}"] = float(prob)
        return out

    def predict(self, X: np.ndarray) -> List[Dict[str, Any]]:
        X = np.asarray(X, np.float64)
        return [self.predict_row(row) for row in X]
