from kfserving_tpu.predictors.pmmlserver.model import PMMLModel  # noqa: F401
