"""PMML predictor (reference python/pmmlserver/pmmlserver/model.py: pypmml
Model.load then evaluate row-wise).  Import-gated like xgbserver."""

from kfserving_tpu.predictors.tabular import TabularModel


class PMMLModel(TabularModel):
    ARTIFACT_EXTENSIONS = (".pmml", ".xml")

    def _load_artifact(self, path: str):
        from pypmml import Model as PmmlModel

        return PmmlModel.load(path)

    def _predict_batch(self, batch):
        # pypmml evaluates row-by-row (reference model.py does the same).
        return [list(self._model.predict(list(row)).values())
                for row in batch]
