"""PMML predictor (reference python/pmmlserver/pmmlserver/model.py: pypmml
Model.load then evaluate row-wise).

pypmml is a JVM bridge and optional; without it the native evaluator
(predictors/pmml_eval.py) parses TreeModel/RegressionModel PMML directly,
returning the same row-wise list(outputs.values()) shape the reference
produces.
"""

from kfserving_tpu.predictors.tabular import TabularModel


class PMMLModel(TabularModel):
    ARTIFACT_EXTENSIONS = (".pmml", ".xml")

    def __init__(self, name: str, model_dir: str):
        super().__init__(name, model_dir)
        self._native = None

    def _load_artifact(self, path: str):
        try:
            from pypmml import Model as PyPmmlModel
        except ImportError:
            from kfserving_tpu.predictors.pmml_eval import PMMLModel as Native

            self._native = Native(path)
            return self._native
        return PyPmmlModel.load(path)

    def _predict_batch(self, batch):
        # Row-by-row evaluation either way (reference model.py does the
        # same); outputs flatten to list(values()) per row.
        if self._native is not None:
            return [list(out.values())
                    for out in self._native.predict(batch)]
        return [list(self._model.predict(list(row)).values())
                for row in batch]
