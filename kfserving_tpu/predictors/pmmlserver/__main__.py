"""`python -m kfserving_tpu.predictors.pmmlserver`."""

import argparse
import logging

from kfserving_tpu.predictors.pmmlserver.model import PMMLModel
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model")
parser.add_argument("--model_dir", required=True)

if __name__ == "__main__":
    args, _ = parser.parse_known_args()
    model = PMMLModel(args.model_name, args.model_dir)
    model.load()
    ModelServer(http_port=args.http_port,
                container_concurrency=args.container_concurrency
                ).start([model])
