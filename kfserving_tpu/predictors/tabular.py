"""Shared base for tabular (CPU, per-batch numpy) predictors.

The reference's sklearn/xgboost/lightgbm/pmml servers are near-identical
~200-line packages (reference python/sklearnserver/sklearnserver/model.py,
python/xgbserver/..., SURVEY.md §2.2): find the artifact in the model dir,
load it with the framework, `np.array(instances)` -> predict.  Here that
shape is one base class; each framework contributes artifact discovery and
a batch-predict function.  They still serve through the same Model contract
and V1/V2 routes as the TPU predictor.
"""

import glob
import logging
import os
from typing import Any, List, Sequence

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError, InvalidInput
from kfserving_tpu.protocol.v2 import InferRequest
from kfserving_tpu.protocol.v2 import make_response as v2_make_response
from kfserving_tpu.storage import Storage

logger = logging.getLogger("kfserving_tpu.predictors.tabular")


class TabularModel(Model):
    """Base: download model_dir, locate an artifact by extension, load it
    with the framework, serve V1 instances through batch predict."""

    ARTIFACT_EXTENSIONS: Sequence[str] = ()

    def __init__(self, name: str, model_dir: str):
        super().__init__(name)
        self.model_dir = model_dir
        self._model = None

    # -- framework hooks ---------------------------------------------------
    def _load_artifact(self, path: str):
        raise NotImplementedError

    def _predict_batch(self, batch: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    def find_artifact(self, local_dir: str) -> str:
        paths: List[str] = []
        for ext in self.ARTIFACT_EXTENSIONS:
            paths += glob.glob(os.path.join(local_dir, f"*{ext}"))
        if len(paths) == 0:
            raise InvalidInput(
                f"no model artifact matching {list(self.ARTIFACT_EXTENSIONS)}"
                f" under {local_dir}")
        if len(paths) > 1:
            # Reference behavior: exactly one model file per server dir
            # (sklearnserver/model.py raises on ambiguity).
            raise InvalidInput(
                f"multiple model artifacts found: {sorted(paths)}")
        return paths[0]

    def load(self) -> bool:
        local_dir = Storage.download(self.model_dir)
        artifact = self.find_artifact(local_dir)
        self._model = self._load_artifact(artifact)
        logger.info("loaded %s from %s", self.name, artifact)
        self.ready = True
        return True

    def unload(self) -> None:
        self._model = None
        self.ready = False

    # -- inference ---------------------------------------------------------
    async def predict(self, request: Any) -> Any:
        if self.predictor_host:
            return await super().predict(request)
        if self._model is None:
            raise InferenceError(f"model {self.name} not loaded")
        if isinstance(request, InferRequest) or (
                isinstance(request, dict)
                and isinstance(request.get("inputs"), list)
                and request["inputs"]
                and isinstance(request["inputs"][0], dict)
                and "datatype" in request["inputs"][0]):
            # V2 (incl. the binary tensor extension): the reference's V2
            # sklearn/xgb path is MLServer speaking the same protocol
            # (predictor_sklearn.go:98-143); single-tensor requests map
            # straight onto the batch-predict hook.
            return self._predict_v2(request)
        instances = v1.get_instances(request)
        try:
            batch = np.asarray(instances)
        except Exception as e:
            raise InvalidInput(f"failed to build batch array: {e}")
        result = self._run(batch)
        if isinstance(result, np.ndarray):
            payload = result.tolist()
        else:
            # Mixed-type rows (e.g. PMML [label, prob, ...]) must not go
            # through np.asarray — it would coerce numbers to strings.
            payload = [r.tolist() if isinstance(r, np.ndarray) else r
                       for r in result]
        return v1.make_response(payload)

    def _predict_v2(self, request: Any) -> Any:
        req = (request if isinstance(request, InferRequest)
               else InferRequest.from_dict(request))
        named = req.named_numpy()
        if len(named) != 1:
            raise InvalidInput(
                f"tabular predictor takes one input tensor, got "
                f"{sorted(named)}")
        batch = next(iter(named.values()))
        result = self._run(batch)
        outputs = (result if isinstance(result, np.ndarray)
                   else np.asarray(result))
        return v2_make_response(self.name, {"output_0": outputs},
                                id=req.id)

    def _run(self, batch: np.ndarray) -> Any:
        try:
            return self._predict_batch(batch)
        except Exception as e:
            raise InferenceError(f"Failed to predict: {e}")
