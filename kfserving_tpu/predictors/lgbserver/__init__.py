from kfserving_tpu.predictors.lgbserver.model import LightGBMModel  # noqa: F401
