"""lightgbm predictor (reference python/lgbserver/lgbserver/model.py:
Booster(model_file=...) then predict).  Import-gated like xgbserver."""

from kfserving_tpu.predictors.tabular import TabularModel


class LightGBMModel(TabularModel):
    ARTIFACT_EXTENSIONS = (".txt", ".lgb")

    def __init__(self, name: str, model_dir: str, nthread: int = 1):
        super().__init__(name, model_dir)
        self.nthread = nthread

    def _load_artifact(self, path: str):
        import lightgbm as lgb

        return lgb.Booster(params={"num_threads": self.nthread},
                           model_file=path)

    def _predict_batch(self, batch):
        return self._model.predict(batch)
