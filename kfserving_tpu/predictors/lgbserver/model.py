"""lightgbm predictor (reference python/lgbserver/lgbserver/model.py:
Booster(model_file=...) then predict).

Like xgbserver, the library is optional: LightGBM's text model format
(.txt, `booster.save_model`) is documented and stable, so without the
library the native evaluator (predictors/trees.py) parses and serves it
with numpy only.
"""

from kfserving_tpu.predictors.tabular import TabularModel


class LightGBMModel(TabularModel):
    ARTIFACT_EXTENSIONS = (".txt", ".lgb")

    def __init__(self, name: str, model_dir: str, nthread: int = 1):
        super().__init__(name, model_dir)
        self.nthread = nthread
        self._native = None

    def _load_artifact(self, path: str):
        try:
            import lightgbm as lgb
        except ImportError:
            from kfserving_tpu.predictors.trees import LightGBMEnsemble

            self._native = LightGBMEnsemble.from_file(path)
            return self._native
        return lgb.Booster(params={"num_threads": self.nthread},
                           model_file=path)

    def _predict_batch(self, batch):
        if self._native is not None:
            return self._native.predict(batch)
        return self._model.predict(batch)
