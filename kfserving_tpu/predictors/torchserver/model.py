"""PyTorch predictor (reference python/pytorchserver/pytorchserver/
model.py): one user-supplied .py file defines the model class, a
`model.pt` state dict restores its weights, V1 instances predict as a
torch batch.

In the TPU build this predictor exists for migration parity — torch
models serve on the host CPU exactly like the reference's CPU path (the
reference's `cuda:0` branch maps to nothing here: accelerated serving
is the jax predictor's job, and torch artifacts convert offline,
SURVEY.md §2.2 "replaced by jaxserver").  The serving semantics match
the reference: exactly one .py file in the model dir, class name from
config (default "PyTorchModel"), strict state-dict load, eval() mode.
"""

import hashlib
import importlib.util
import logging
import os
import sys
from typing import Any

import numpy as np

from kfserving_tpu.model.model import Model
from kfserving_tpu.protocol import v1
from kfserving_tpu.protocol.errors import InferenceError, InvalidInput
from kfserving_tpu.storage import Storage

logger = logging.getLogger("kfserving_tpu.predictors.torchserver")

PYTORCH_FILE = "model.pt"


class PyTorchModel(Model):
    def __init__(self, name: str, model_dir: str,
                 model_class_name: str = "PyTorchModel"):
        super().__init__(name)
        self.model_dir = model_dir
        self.model_class_name = model_class_name
        self._model = None

    def load(self) -> bool:
        import torch

        local_dir = Storage.download(self.model_dir)
        model_file = os.path.join(local_dir, PYTORCH_FILE)
        if not os.path.exists(model_file):
            raise InvalidInput(f"missing {PYTORCH_FILE} under {local_dir}")
        py_files = [f for f in os.listdir(local_dir) if f.endswith(".py")]
        if len(py_files) == 0:
            raise InvalidInput("Missing PyTorch Model Class File.")
        if len(py_files) > 1:
            # Reference contract: exactly one Python file per model dir.
            raise InvalidInput(
                f"More than one Python file is detected: {sorted(py_files)}")
        # Unique module identity per model dir: two models whose class
        # files share a filename (net.py) must not alias each other's
        # cached module (multi-model serving in one process).
        class_file = os.path.join(local_dir, py_files[0])
        module_name = ("kfserving_tpu._torch_user_"
                       + hashlib.sha1(class_file.encode()).hexdigest()[:12])
        spec = importlib.util.spec_from_file_location(
            module_name, class_file)
        module = importlib.util.module_from_spec(spec)
        sys.modules[module_name] = module
        spec.loader.exec_module(module)
        model_class = getattr(module, self.model_class_name)
        self._model = model_class()
        self._model.load_state_dict(
            torch.load(model_file, map_location="cpu",
                       weights_only=True))
        self._model.eval()
        logger.info("loaded torch model %s (%s) from %s",
                    self.name, self.model_class_name, local_dir)
        self.ready = True
        return True

    def unload(self) -> None:
        self._model = None
        self.ready = False

    async def predict(self, request: Any) -> Any:
        if self.predictor_host:
            return await super().predict(request)
        import torch

        if self._model is None:
            raise InferenceError(f"model {self.name} not loaded")
        instances = v1.get_instances(request)
        try:
            batch = torch.as_tensor(np.asarray(instances,
                                               dtype=np.float32))
        except Exception as e:
            raise InvalidInput(
                f"Failed to initialize Torch Tensor from inputs: {e}")
        try:
            with torch.no_grad():
                out = self._model(batch)
        except Exception as e:
            raise InferenceError(f"Failed to predict: {e}")
        return v1.make_response(out.numpy().tolist())
