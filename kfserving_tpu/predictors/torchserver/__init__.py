from kfserving_tpu.predictors.torchserver.model import PyTorchModel

__all__ = ["PyTorchModel"]
