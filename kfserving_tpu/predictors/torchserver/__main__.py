"""`python -m kfserving_tpu.predictors.torchserver` — args as the
reference server (`--model_name --model_dir --model_class_name`,
reference python/pytorchserver/pytorchserver/__main__.py)."""

import argparse
import logging

from kfserving_tpu.predictors.torchserver.model import PyTorchModel
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model")
parser.add_argument("--model_dir", required=True)
parser.add_argument("--model_class_name", default="PyTorchModel")

if __name__ == "__main__":
    args, _ = parser.parse_known_args()
    model = PyTorchModel(args.model_name, args.model_dir,
                         args.model_class_name)
    model.load()
    ModelServer(http_port=args.http_port,
                container_concurrency=args.container_concurrency
                ).start([model])
