from kfserving_tpu.predictors.jax_model import (  # noqa: F401
    JaxModel,
    JaxModelConfig,
)
from kfserving_tpu.predictors.jaxserver.repository import (  # noqa: F401
    JaxModelRepository,
)
