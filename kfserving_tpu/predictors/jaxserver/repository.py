"""Multi-model repository for JAX models.

Maps the reference's per-framework model repositories (e.g.
python/sklearnserver/sklearnserver/sklearn_model_repository.py) to the TPU
predictor, with the addition that loads/unloads go through one shared
HBMManager: "loaded" on TPU means resident in HBM, so admission can evict
LRU models (SURVEY.md §7 hard parts — the reference's disk-based
load/unload in pkg/agent/puller.go:120-183 had no such constraint).

With residency (the default), the repository is DEMAND-PAGED
(engine/residency.py): `load` is declarative registration — host-side
prep only, the model becomes addressable with no device memory — and
the predict path transparently faults models into HBM, with
admission-aware LRU eviction making room.  Hundreds of models register
against one device; the HBM budget bounds how many serve concurrently.
`residency=False` restores the eager load-is-resident behavior
(each load admits immediately and eviction unloads the victim).
"""

import logging
import os
from typing import List, Optional

from kfserving_tpu.engine.hbm import HBMManager
from kfserving_tpu.model.repository import MODEL_MOUNT_DIRS, ModelRepository
from kfserving_tpu.predictors.jax_model import DEFAULT_CONFIG_NAME, JaxModel

logger = logging.getLogger("kfserving_tpu.jaxserver")


class JaxModelRepository(ModelRepository):
    def __init__(self, models_dir: str = MODEL_MOUNT_DIRS,
                 hbm: Optional[HBMManager] = None,
                 residency: bool = True):
        super().__init__(models_dir)
        self.hbm = hbm or HBMManager()
        if residency:
            from kfserving_tpu.engine.residency import ResidencyManager

            # The manager owns eviction end to end: admission-aware
            # victim choice against the ledger, physical offload of the
            # victims (host mmap params retained for the warm fault
            # back in).
            self.residency: Optional[ResidencyManager] = \
                ResidencyManager(self.hbm)
        else:
            self.residency = None
            # Legacy eager mode: accounting decides *who*, the
            # repository performs the unload that actually frees HBM.
            self.hbm.evict_cb = self._evict

    def _evict(self, name: str) -> None:
        model = self.get_model(name)
        if model is not None:
            model.unload()

    def _catalog_dir(self) -> str:
        """Resolve the catalog root once: models_dir may arrive as a
        storage URI (the isvc spec's storage_uri, e.g. `file://...`) —
        resolve it through Storage so both the boot registration sweep
        and per-model load address a real directory.  Blocking for
        remote schemes; callers already run off-loop."""
        if not os.path.isdir(self.models_dir):
            from kfserving_tpu.storage import Storage

            self.models_dir = Storage.download(self.models_dir)
        return self.models_dir

    def _model_for(self, name: str) -> Optional[JaxModel]:
        model = self.get_model(name)
        if model is None:
            model_dir = os.path.join(self._catalog_dir(), name)
            if not os.path.isdir(model_dir):
                return None
            model = JaxModel(name, model_dir, hbm=self.hbm,
                             residency=self.residency)
            self.update(model)
        return model

    async def load(self, name: str) -> bool:
        """Make <models_dir>/<name> servable (agent puller load path:
        POST /v2/repository/models/{name}/load after download).  Under
        residency this is declarative registration — host prep only,
        first predict faults the model in; eager mode builds and
        admits the engine here."""
        model = self._model_for(name)
        if model is None:
            return False
        if self.residency is not None:
            return bool(await _to_thread(model.register))
        return bool(await _to_thread(model.load))

    def register_all(self) -> List[str]:
        """Declaratively register every model directory under
        models_dir (blocking; callers run it off-loop).  The
        multi-model replica boot path: N models become addressable in
        O(N) file reads, no device work."""
        if self.residency is None:
            raise RuntimeError(
                "register_all requires residency mode")
        names = []
        root = self._catalog_dir()
        for name in sorted(os.listdir(root)):
            if not os.path.exists(os.path.join(
                    root, name, DEFAULT_CONFIG_NAME)):
                continue
            # Per-model isolation, the TrainedModel contract: one
            # corrupt config.json must not make the other N-1 models
            # unservable (the bad entry just stays unregistered).
            try:
                model = self._model_for(name)
                if model is not None and model.register():
                    names.append(name)
            except Exception:
                logger.exception(
                    "registration of model %r failed; continuing "
                    "catalog sweep", name)
                self.models.pop(name, None)
        return names


async def _to_thread(fn):
    """Model loading compiles on-device; keep it off the serving loop."""
    import asyncio

    return await asyncio.get_running_loop().run_in_executor(None, fn)
