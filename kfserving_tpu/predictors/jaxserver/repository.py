"""Multi-model repository for JAX models.

Maps the reference's per-framework model repositories (e.g.
python/sklearnserver/sklearnserver/sklearn_model_repository.py) to the TPU
predictor, with the addition that loads/unloads go through one shared
HBMManager: "loaded" on TPU means resident in HBM, so admission can evict
LRU models (SURVEY.md §7 hard parts — the reference's disk-based
load/unload in pkg/agent/puller.go:120-183 had no such constraint).
"""

import os
from typing import Optional

from kfserving_tpu.engine.hbm import HBMManager
from kfserving_tpu.model.repository import MODEL_MOUNT_DIRS, ModelRepository
from kfserving_tpu.predictors.jax_model import JaxModel


class JaxModelRepository(ModelRepository):
    def __init__(self, models_dir: str = MODEL_MOUNT_DIRS,
                 hbm: Optional[HBMManager] = None):
        super().__init__(models_dir)
        self.hbm = hbm or HBMManager()
        # The repository owns eviction: accounting decides *who*, the
        # repository performs the unload that actually frees HBM.
        self.hbm.evict_cb = self._evict

    def _evict(self, name: str) -> None:
        model = self.get_model(name)
        if model is not None:
            model.unload()

    async def load(self, name: str) -> bool:
        """Load <models_dir>/<name> as a JaxModel (agent puller load path:
        POST /v2/repository/models/{name}/load after download)."""
        model = self.get_model(name)
        if model is None:
            model_dir = os.path.join(self.models_dir, name)
            if not os.path.isdir(model_dir):
                return False
            model = JaxModel(name, model_dir, hbm=self.hbm)
            self.update(model)
        return bool(await _to_thread(model.load))


async def _to_thread(fn):
    """Model loading compiles on-device; keep it off the serving loop."""
    import asyncio

    return await asyncio.get_running_loop().run_in_executor(None, fn)
