"""jaxserver entrypoint: `python -m kfserving_tpu.predictors.jaxserver`.

Args mirror the reference model-server convention (`--model_name
--model_dir --http_port [--workers]`, reference
pkg/apis/serving/v1beta1/predictor_sklearn.go:77-96 builds exactly these)
plus the TPU batching knobs.
"""

import argparse
import logging

from kfserving_tpu.engine.compile_cache import enable as enable_compile_cache
from kfserving_tpu.predictors.jax_model import JaxModel
from kfserving_tpu.predictors.jaxserver.repository import JaxModelRepository
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model",
                    help="name under which the model is served")
parser.add_argument("--model_dir", required=True,
                    help="model artifact URI (local path, gs://, s3://...)")
parser.add_argument("--multi_model", action="store_true",
                    help="treat model_dir as a repository of models loaded "
                         "on demand via /v2/repository/models/{name}/load")
args, _ = parser.parse_known_args()

if __name__ == "__main__":
    enable_compile_cache()
    if args.multi_model:
        repo = JaxModelRepository(models_dir=args.model_dir)
        server = ModelServer(http_port=args.http_port,
                             registered_models=repo)
        server.start([])
    else:
        model = JaxModel(args.model_name, args.model_dir)
        model.load()
        ModelServer(http_port=args.http_port).start([model])
