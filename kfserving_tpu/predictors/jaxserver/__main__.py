"""jaxserver entrypoint: `python -m kfserving_tpu.predictors.jaxserver`.

Args mirror the reference model-server convention (`--model_name
--model_dir --http_port [--workers]`, reference
pkg/apis/serving/v1beta1/predictor_sklearn.go:77-96 builds exactly these)
plus the reference agent's flags, served in-process (reference
cmd/agent/main.go:32-55): payload logging (--log_url/--log_mode), the
multi-model puller (--config_dir), and the TPU batching knobs.
"""

import argparse
import logging

from kfserving_tpu.engine.compile_cache import enable as enable_compile_cache
from kfserving_tpu.predictors.jax_model import JaxModel
from kfserving_tpu.predictors.jaxserver.repository import JaxModelRepository
from kfserving_tpu.server.app import ModelServer, parser as server_parser

logging.basicConfig(level=logging.INFO)

parser = argparse.ArgumentParser(parents=[server_parser])
parser.add_argument("--model_name", default="model",
                    help="name under which the model is served")
parser.add_argument("--model_dir", required=True,
                    help="model artifact URI (local path, gs://, s3://...) "
                         "or, with --multi_model, the models root dir")
parser.add_argument("--multi_model", action="store_true",
                    help="serve a repository of models loaded on demand "
                         "via /v2/repository/models/{name}/load")
parser.add_argument("--config_dir", default=None,
                    help="model-config file/dir to watch for multi-model "
                         "serving (agent --config-dir equivalent)")
parser.add_argument("--log_url", default=None,
                    help="CloudEvents sink for payload logging "
                         "(agent --log-url equivalent)")
parser.add_argument("--log_mode", default="all",
                    choices=["all", "request", "response"])
parser.add_argument("--source_uri", default="",
                    help="CloudEvents source attribute")


def build_server(args) -> ModelServer:
    cc = getattr(args, "container_concurrency", 0)
    grpc_port = getattr(args, "grpc_port", None)
    multi_model = args.multi_model or args.config_dir
    if multi_model:
        repo = JaxModelRepository(models_dir=args.model_dir)
        server = ModelServer(http_port=args.http_port,
                             registered_models=repo,
                             container_concurrency=cc,
                             grpc_port=grpc_port)
    else:
        server = ModelServer(http_port=args.http_port,
                             container_concurrency=cc,
                             grpc_port=grpc_port)

    if args.config_dir:
        import asyncio

        from kfserving_tpu.agent import Downloader, ModelConfigWatcher, Puller

        events: asyncio.Queue = asyncio.Queue()
        watcher = ModelConfigWatcher(args.config_dir, events=events)
        puller = Puller(server.repository,
                        Downloader(args.model_dir), events=events)
        server.services += [watcher, puller]

    if args.log_url:
        from kfserving_tpu.agent import RequestLogger

        request_logger = RequestLogger(
            args.log_url, source_uri=args.source_uri,
            log_mode=args.log_mode)
        request_logger.attach(server)
        server.services.append(request_logger)
    return server


if __name__ == "__main__":
    import os

    args, _ = parser.parse_known_args()
    enable_compile_cache()
    server = build_server(args)
    if args.multi_model or args.config_dir:
        server.start([])
    elif os.environ.get("KFS_STANDBY"):
        # Recycle fast-swap: imports and server setup are done, but the
        # model load (device init + compile) waits for the orchestrator
        # to POST /standby/activate once the predecessor releases the
        # chip (subprocess_orchestrator recycle path).
        model = JaxModel(args.model_name, args.model_dir)
        server.standby_model(lambda: (model.load(), model)[1])
        server.start([])
    else:
        model = JaxModel(args.model_name, args.model_dir)
        model.load()
        server.start([model])
