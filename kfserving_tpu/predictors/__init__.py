"""Per-framework predictors on the Model SDK.

The reference ships one server package per framework
(python/{sklearnserver,xgbserver,lgbserver,pmmlserver,pytorchserver},
SURVEY.md §2.2); here each is a Model subclass plus a repository and a
`python -m kfserving_tpu.predictors.<name>` entrypoint.  The TPU-native
predictor is `jaxserver` — the replacement for the reference's
pytorchserver and the reason this framework exists.
"""
