"""sklearn predictor (reference python/sklearnserver/sklearnserver/
model.py:32-53: joblib/pickle load, np.array(instances) -> model.predict).

The CPU baseline predictor of BASELINE.json config #1 (sklearn-iris V1,
reference test/e2e/predictor/test_sklearn.py asserts predictions [1, 1])."""

import os
import pickle
from typing import Optional

from kfserving_tpu.model.repository import MODEL_MOUNT_DIRS, ModelRepository
from kfserving_tpu.predictors.tabular import TabularModel


class SKLearnModel(TabularModel):
    ARTIFACT_EXTENSIONS = (".joblib", ".pkl", ".pickle")

    def _load_artifact(self, path: str):
        if path.endswith(".joblib"):
            import joblib

            return joblib.load(path)
        with open(path, "rb") as f:
            return pickle.load(f)  # noqa: S301 - trusted model artifact

    def _predict_batch(self, batch):
        return self._model.predict(batch)


class SKLearnModelRepository(ModelRepository):
    def __init__(self, models_dir: str = MODEL_MOUNT_DIRS):
        super().__init__(models_dir)

    async def load(self, name: str) -> bool:
        model = self.get_model(name)
        if model is None:
            model_dir = os.path.join(self.models_dir, name)
            if not os.path.isdir(model_dir):
                return False
            model = SKLearnModel(name, model_dir)
            self.update(model)
        return bool(model.load())
