from kfserving_tpu.predictors.sklearnserver.model import (  # noqa: F401
    SKLearnModel,
    SKLearnModelRepository,
)
