"""Model puller: watcher events -> download -> repository load/unload.

Reference semantics (pkg/agent/puller.go:62-183): one event channel in, a
per-model goroutine so ops on the *same* model serialize while different
models pull in parallel; completed ops retire the per-model channel when
drained.  The in-process version keeps the same shape with per-model
asyncio queues and hands loaded artifacts straight to the ModelRepository
(the reference POSTs localhost:8080/v2/repository/models/{m}/load|unload,
puller.go:137-176 — same observable contract, minus the HTTP hop).
"""

import asyncio
import logging
from typing import Dict, Optional

from kfserving_tpu.agent.downloader import Downloader
from kfserving_tpu.reliability import RetryPolicy

logger = logging.getLogger("kfserving_tpu.agent.puller")


class Puller:
    def __init__(self, repository, downloader: Downloader,
                 events: Optional[asyncio.Queue] = None,
                 retry: Optional[RetryPolicy] = None):
        self.repository = repository
        self.downloader = downloader
        self.events: asyncio.Queue = events or asyncio.Queue()
        # Model pulls retry with backoff (KFS_PULLER_RETRY_* knobs):
        # a transient storage flake must not strand a model unloaded
        # until the next config event (the reference leans on k8s
        # restart + the TF-Serving retried-load discipline).  The
        # attempts NEST: the storage layer owns per-download transient
        # replay (3 by default), so this outer policy guards only the
        # agent-level edge and defaults to 2 — worst case 2x3, not the
        # 3x3 (or KFS_RETRY_MAX_ATTEMPTS²) a symmetric default
        # multiplies to.
        self.retry = retry or RetryPolicy.from_env(
            "KFS_PULLER", default_max_attempts=2)
        self._per_model: Dict[str, asyncio.Queue] = {}
        self._workers: Dict[str, asyncio.Task] = {}
        self._task: Optional[asyncio.Task] = None
        self.ops_ok = 0
        self.ops_failed = 0

    async def start(self):
        self._task = asyncio.create_task(self._dispatch())

    async def stop(self):
        tasks = list(self._workers.values())
        if self._task is not None:
            tasks.append(self._task)
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        self._workers.clear()
        self._per_model.clear()
        self._task = None

    async def _dispatch(self):
        """Fan events out to per-model workers (ops on one model serialize,
        different models proceed concurrently — puller.go:83-94)."""
        while True:
            op, name, spec = await self.events.get()
            q = self._per_model.get(name)
            if q is None:
                q = asyncio.Queue()
                self._per_model[name] = q
                self._workers[name] = asyncio.create_task(
                    self._model_worker(name, q))
            await q.put((op, spec))
            self.events.task_done()

    async def _model_worker(self, name: str, q: asyncio.Queue):
        while True:
            op, spec = await q.get()
            try:
                if op == "load":
                    await self._load(name, spec)
                elif op == "unload":
                    await self._unload(name)
                self.ops_ok += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                self.ops_failed += 1
                logger.exception("%s of model %s failed", op, name)
            finally:
                q.task_done()
                if q.empty():
                    # Retire the idle worker (reference drains and deletes
                    # the channel, puller.go:120-134); a later event simply
                    # spawns a fresh one.
                    self._per_model.pop(name, None)
                    self._workers.pop(name, None)
                    return

    async def _load(self, name: str, spec: dict):
        loop = asyncio.get_running_loop()

        def pull():
            return loop.run_in_executor(
                None, self.downloader.download, name, spec)

        # Retry the pull (idempotent: the downloader wipes a partial
        # generation and writes its marker only on success); backoff
        # sleeps yield the loop so other models keep pulling.
        await self.retry.acall(pull)
        ok = await self.repository.load(name)
        if not ok:
            raise RuntimeError(f"repository refused to load {name}")
        logger.info("model %s loaded", name)

    async def _unload(self, name: str):
        try:
            await self.repository.unload(name)
        except KeyError:
            # Never-successfully-loaded model removed from the config:
            # expected no-op, not a failure (its load may have errored).
            logger.info("model %s was not loaded; nothing to unload", name)
            return
        logger.info("model %s unloaded", name)

    def stats(self) -> dict:
        return {"ops_ok": self.ops_ok, "ops_failed": self.ops_failed,
                "active_models": len(self._workers)}
