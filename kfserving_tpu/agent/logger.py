"""Payload logger: tee request/response bodies as CloudEvents to a sink.

Reference semantics (pkg/logger/):
- event types `org.kubeflow.serving.inference.request` / `.response`
  (reference logger/worker.go:29-42);
- CE extensions inferenceservicename / namespace / endpoint / component
  (reference logger/worker.go:97-113);
- a dispatcher with a bounded queue (100) and a fixed worker pool (5)
  (reference logger/dispatcher.go:25-48);
- log modes all | request | response (reference
  pkg/apis/serving/v1beta1/inference_service.go:56-64).

In-process: the logger attaches to ModelServer.request_hooks, so the tee
happens after the response is computed with zero extra serialization of the
hot path; drops (queue full) increment a counter instead of blocking
serving — same backpressure decision as the reference's buffered channel.
"""

import asyncio
import json
import logging
import uuid
from enum import Enum
from typing import Optional

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.tracing import current_request_id

logger = logging.getLogger("kfserving_tpu.agent.logger")

CE_TYPE_REQUEST = "org.kubeflow.serving.inference.request"
CE_TYPE_RESPONSE = "org.kubeflow.serving.inference.response"
DEFAULT_WORKERS = 5   # reference dispatcher.go:25
QUEUE_SIZE = 100      # reference dispatcher.go:30


class LogMode(str, Enum):
    all = "all"
    request = "request"
    response = "response"


class RequestLogger:
    """Async CloudEvents tee.  Call start() inside a running loop; attach()
    wires it into a ModelServer."""

    def __init__(self, log_url: str, source_uri: str = "",
                 log_mode: LogMode = LogMode.all,
                 inference_service: str = "", namespace: str = "",
                 endpoint: str = "", component: str = "predictor",
                 workers: int = DEFAULT_WORKERS,
                 queue_size: int = QUEUE_SIZE):
        self.log_url = log_url
        self.source_uri = source_uri
        self.log_mode = LogMode(log_mode)
        self.inference_service = inference_service
        self.namespace = namespace
        self.endpoint = endpoint
        self.component = component
        self.workers = workers
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=queue_size)
        self.dropped = 0
        self.sent = 0
        self.failed = 0
        self._tasks = []
        self._session = None

    # -- lifecycle ---------------------------------------------------------
    async def start(self):
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30))
        self._tasks = [asyncio.create_task(self._worker())
                       for _ in range(self.workers)]

    async def stop(self):
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        if self._session is not None:
            await self._session.close()
            self._session = None

    # -- hot path ----------------------------------------------------------
    def log(self, model: str, verb: str, kind: str, payload: bytes,
            request_id: Optional[str] = None, status: int = 200):
        """Enqueue one event; never blocks the serving path."""
        if self.log_mode == LogMode.request and kind != "request":
            return
        if self.log_mode == LogMode.response and kind != "response":
            return
        event = {
            "specversion": "1.0",
            "id": request_id or str(uuid.uuid4()),
            "type": (CE_TYPE_REQUEST if kind == "request"
                     else CE_TYPE_RESPONSE),
            "source": self.source_uri or f"http://localhost/models/{model}",
            "datacontenttype": "application/json",
            "inferenceservicename": self.inference_service,
            "namespace": self.namespace,
            "endpoint": self.endpoint,
            "component": self.component,
            "model": model,
            "verb": verb,
            "status": str(status),
        }
        try:
            self.queue.put_nowait((event, payload))
        except asyncio.QueueFull:
            if self.dropped == 0:
                # Warn ONCE: sustained overload would otherwise log a
                # line per mirrored payload — the registry counter is
                # the ongoing signal, this line is the page.
                logger.warning(
                    "payload log queue full (size %d): dropping "
                    "events (kfserving_tpu_payload_log_total"
                    "{outcome=\"dropped\"} counts further drops)",
                    self.queue.maxsize)
            self.dropped += 1
            obs.payload_log_total().labels(outcome="dropped").inc()
        obs.payload_log_queued().set(self.queue.qsize())

    def attach(self, server) -> None:
        """Hook into a ModelServer: tees both directions per request with a
        shared CE id (reference pairs request/response by id,
        logger/handler.go:85-124).

        The CE id is the request's ACTIVE trace id (the W3C/x-request-id
        the tracing contextvar carries at hook time), so payload events
        join the distributed trace — a drifted payload links straight to
        its spans at /debug/traces.  A fresh uuid only when untraced."""
        def hook(name, verb, req, resp, latency_ms):
            rid = current_request_id.get() or str(uuid.uuid4())
            status = resp.status if resp is not None else 200
            self.log(name, verb, "request", req.body, request_id=rid,
                     status=status)
            if resp is not None:
                self.log(name, verb, "response", resp.body,
                         request_id=rid, status=status)

        server.request_hooks.append(hook)

    # -- workers -----------------------------------------------------------
    async def _worker(self):
        while True:
            event, payload = await self.queue.get()
            try:
                await self._send(event, payload)
                self.sent += 1
                obs.payload_log_total().labels(outcome="sent").inc()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.failed += 1
                obs.payload_log_total().labels(outcome="failed").inc()
                logger.warning("log sink send failed: %s", e)
            finally:
                self.queue.task_done()
                obs.payload_log_queued().set(self.queue.qsize())

    async def _send(self, event: dict, payload: bytes):
        # Binary CloudEvents encoding: attributes -> ce- headers.
        headers = {"content-type": event["datacontenttype"]}
        for key in ("specversion", "id", "type", "source",
                    "inferenceservicename", "namespace", "endpoint",
                    "component", "model", "verb", "status"):
            if event.get(key):
                headers[f"ce-{key}"] = event[key]
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        async with self._session.post(
                self.log_url, data=payload or b"", headers=headers) as resp:
            if resp.status >= 400:
                raise RuntimeError(f"sink returned {resp.status}")

    def stats(self) -> dict:
        """Instance snapshot.  The same numbers export as registry
        series (`kfserving_tpu_payload_log_total{outcome=...}` and
        `kfserving_tpu_payload_log_queued`) so /metrics scrapers see
        them without holding the logger object."""
        return {"sent": self.sent, "failed": self.failed,
                "dropped": self.dropped, "queued": self.queue.qsize()}


def structured_event(event: dict, payload: bytes) -> dict:
    """Structured-mode encoding helper (tests / alternative sinks)."""
    data: object = payload
    try:
        data = json.loads(payload)
    except Exception:
        if isinstance(payload, (bytes, bytearray)):
            data = payload.decode("utf-8", "replace")
    return {**event, "data": data}
