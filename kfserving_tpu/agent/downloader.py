"""Idempotent model artifact download.

Reference semantics (pkg/agent/downloader.go:42-75): each successful
download of (model, spec) drops a `SUCCESS.<sha256(spec)>` marker inside the
model dir; a restart that finds the marker skips the pull entirely, and a
changed spec (different storageUri/version) hashes differently so it
re-downloads.  Stale markers from previous specs are removed on success.
"""

import hashlib
import json
import logging
import os
import shutil
from typing import Optional

from kfserving_tpu.storage import Storage

logger = logging.getLogger("kfserving_tpu.agent.downloader")

SUCCESS_PREFIX = "SUCCESS"


def spec_digest(spec: dict) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()


class Downloader:
    def __init__(self, model_dir: str):
        self.model_dir = model_dir
        os.makedirs(model_dir, exist_ok=True)

    def model_path(self, model_name: str) -> str:
        return os.path.join(self.model_dir, model_name)

    def _marker(self, model_name: str, digest: str) -> str:
        return os.path.join(self.model_path(model_name),
                            f"{SUCCESS_PREFIX}.{digest}")

    def is_downloaded(self, model_name: str, spec: dict) -> bool:
        return os.path.exists(self._marker(model_name, spec_digest(spec)))

    def download(self, model_name: str, spec: dict) -> Optional[str]:
        """Download spec["storageUri"] into <model_dir>/<model_name>.
        Returns the local path, or None when already current.

        Replay-safe by construction: the marker lands only after a
        full pull and a changed/partial generation is wiped first, so
        the puller's retry policy can re-invoke this freely (the
        `agent.pull` fault site injects failures here, before any
        filesystem mutation)."""
        from kfserving_tpu.reliability import fault_sites, faults

        faults.inject_sync(fault_sites.AGENT_PULL, key=model_name)
        digest = spec_digest(spec)
        target = self.model_path(model_name)
        marker = self._marker(model_name, digest)
        if os.path.exists(marker):
            logger.info("model %s already downloaded (marker %s)",
                        model_name, os.path.basename(marker))
            return None
        # A changed spec invalidates the previous artifact wholesale: remove
        # the dir so partial/stale files can't mix generations (the
        # reference keeps per-file hashes; whole-dir replace is simpler and
        # safe because serving reads only after load()).
        if os.path.isdir(target):
            shutil.rmtree(target)
        os.makedirs(target, exist_ok=True)
        Storage.download(spec["storageUri"], target)
        with open(marker, "w") as f:
            f.write(digest)
        logger.info("downloaded %s from %s", model_name, spec["storageUri"])
        return target
