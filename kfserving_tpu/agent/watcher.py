"""Model-config watcher: config file changes -> load/unload events.

Reference semantics (pkg/agent/watcher.go:79-170): watch the mounted
ConfigMap volume for kubelet's atomic `..data` symlink swap, reparse
`models.json` ([{modelName, modelSpec:{storageUri, framework, memory}}] —
pkg/modelconfig/configmap.go:34-51), diff against the in-memory view, and
emit per-model ops: Add (new), Remove (gone), and re-Add for changed specs
(the reference marks those ShouldDownload, watcher.go:150-165).

In-process: no inotify dependency — an asyncio poll loop hashes the
resolved config content.  Poll interval 1s matches kubelet's sync
granularity well enough for serving (the reference's fsnotify is also
bounded by kubelet's update cadence, not the notification hop).
"""

import asyncio
import hashlib
import json
import logging
import os
from typing import Dict, Optional, Tuple

logger = logging.getLogger("kfserving_tpu.agent.watcher")

MODEL_CONFIG_FILE = "models.json"


def parse_model_config(raw: bytes) -> Dict[str, dict]:
    """models.json -> {name: spec}.  Invalid entries are skipped with a
    warning (one bad model must not take down the others)."""
    try:
        entries = json.loads(raw or b"[]")
    except ValueError as e:
        raise ValueError(f"invalid model config: {e}")
    if not isinstance(entries, list):
        # A dict/scalar here is a config typo, not "zero models" — treating
        # it as empty would silently unload the whole fleet.
        raise ValueError(
            f"invalid model config: expected a JSON list, got "
            f"{type(entries).__name__}")
    out: Dict[str, dict] = {}
    for entry in entries:
        if not isinstance(entry, dict):
            logger.warning("skipping invalid model config entry: %r", entry)
            continue
        name = entry.get("modelName")
        spec = entry.get("modelSpec")
        if not name or not isinstance(spec, dict) or \
                "storageUri" not in spec:
            logger.warning("skipping invalid model config entry: %r", entry)
            continue
        out[name] = spec
    return out


def diff_configs(old: Dict[str, dict], new: Dict[str, dict]
                 ) -> Tuple[Dict[str, dict], Dict[str, dict], list]:
    """Returns (added_or_changed, unchanged, removed_names)."""
    added = {n: s for n, s in new.items()
             if n not in old or old[n] != s}
    unchanged = {n: s for n, s in new.items()
                 if n in old and old[n] == s}
    removed = [n for n in old if n not in new]
    return added, unchanged, removed


class ModelConfigWatcher:
    """Polls a model-config path and pushes ("load"|"unload", name, spec)
    events onto `events` (consumed by the Puller)."""

    def __init__(self, config_path: str,
                 events: Optional[asyncio.Queue] = None,
                 poll_interval: float = 1.0):
        self.config_path = config_path
        self.events: asyncio.Queue = events or asyncio.Queue()
        self.poll_interval = poll_interval
        self.current: Dict[str, dict] = {}
        self._digest: Optional[str] = None
        self._task: Optional[asyncio.Task] = None

    def _resolve(self) -> str:
        """ConfigMap volumes present the file through a `..data` symlink
        dir; accept either the file itself or a directory containing it."""
        path = self.config_path
        if os.path.isdir(path):
            path = os.path.join(path, MODEL_CONFIG_FILE)
        return path

    def _read(self) -> Optional[bytes]:
        try:
            with open(self._resolve(), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    async def sync(self) -> bool:
        """One reconcile pass; returns True if events were emitted.

        The config read runs in an executor (kfslint async-blocking):
        ConfigMap volumes are network-backed mounts, and the watcher
        shares the agent's loop with live pulls."""
        raw = await asyncio.get_running_loop().run_in_executor(
            None, self._read)
        if raw is None:
            return False
        digest = hashlib.sha256(raw).hexdigest()
        if digest == self._digest:
            return False
        try:
            new = parse_model_config(raw)
        except ValueError as e:
            logger.error("%s", e)
            return False
        added, _, removed = diff_configs(self.current, new)
        for name in removed:
            await self.events.put(("unload", name, self.current[name]))
        for name, spec in added.items():
            await self.events.put(("load", name, spec))
        self.current = new
        self._digest = digest
        if added or removed:
            logger.info("model config sync: +%d -%d",
                        len(added), len(removed))
        return bool(added or removed)

    async def start(self):
        self._task = asyncio.create_task(self._loop())

    async def _loop(self):
        while True:
            try:
                await self.sync()
            except Exception:
                logger.exception("model config sync failed")
            await asyncio.sleep(self.poll_interval)

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
