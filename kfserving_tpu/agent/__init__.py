"""Agent capabilities, in-process.

The reference runs these in a Go sidecar binary (reference cmd/agent/
main.go:289-323 chains proxy -> batcher -> logger; pkg/agent runs the
model puller): payload logging, model pulling for multi-model serving, and
readiness probing.  The TPU build runs them as asyncio tasks inside the
model server process — no HTTP hairpin between sidecar and server
(SURVEY.md §7.3-7.4), which also lets the puller hand models straight to
the HBM-aware repository instead of POSTing localhost.

- logger.py:     CloudEvents request/response tee with a bounded worker
                 pool (reference pkg/logger: 5 workers, queue 100).
- downloader.py: idempotent artifact download with SUCCESS.<sha> markers
                 (reference pkg/agent/downloader.go:42-75).
- watcher.py:    model-config file watcher with kubelet ..data symlink-swap
                 semantics (reference pkg/agent/watcher.go:79-170).
- puller.py:     per-model serialized load/unload pipeline (reference
                 pkg/agent/puller.go:62-183).
"""

from kfserving_tpu.agent.downloader import Downloader  # noqa: F401
from kfserving_tpu.agent.logger import LogMode, RequestLogger  # noqa: F401
from kfserving_tpu.agent.puller import Puller  # noqa: F401
from kfserving_tpu.agent.watcher import ModelConfigWatcher  # noqa: F401
