"""Protocol-agnostic data-plane operations over a ModelRepository.

This is the glue between HTTP handlers and models, the analogue of the
reference's handler bodies (reference python/kfserving/kfserving/handlers/
http.py:53-112 and kfserver.py:118-196), factored so gRPC or in-process
callers reuse the same path.
"""

import json
from typing import Any, Dict, List

from kfserving_tpu import __version__ as SERVER_VERSION
from kfserving_tpu.model.model import Model
from kfserving_tpu.model.repository import ModelRepository, maybe_await
from kfserving_tpu.protocol import cloudevents, native, v1, v2
from kfserving_tpu.protocol.errors import (
    InvalidInput,
    ModelNotFound,
    ModelNotReady,
)
from kfserving_tpu.protocol.v2 import InferRequest
from kfserving_tpu.reliability.deadline import (
    check_deadline,
    deadline_scope,
)
from kfserving_tpu.reliability import fault_sites
from kfserving_tpu.reliability.faults import faults
from kfserving_tpu.tracing import tracer

SERVER_NAME = "kfserving-tpu"


class DataPlane:
    def __init__(self, repository: ModelRepository):
        self.repository = repository

    # -- health / metadata -------------------------------------------------
    def live(self) -> bool:
        return True

    def server_ready(self) -> bool:
        """V2 "server ready": all registered models ready (required_api.md)."""
        return all(m.ready for m in self.repository.get_models())

    def model_ready(self, name: str) -> Model:
        model = self.repository.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        if not model.ready:
            raise ModelNotReady(name)
        return model

    def list_models(self) -> List[str]:
        return [m.name for m in self.repository.get_models()]

    def server_metadata(self) -> Dict[str, Any]:
        return {
            "name": SERVER_NAME,
            "version": SERVER_VERSION,
            "extensions": ["model_repository"],
        }

    def model_metadata(self, name: str) -> Dict[str, Any]:
        model = self.repository.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        return model.metadata()

    # -- inference ---------------------------------------------------------
    async def get_model(self, name: str) -> Model:
        """Fetch a model, lazily loading on first use like the reference
        (handlers/http.py:32-41).

        The load runs OUTSIDE the request's deadline scope: a lazy
        load (download + compile grid, multi-second) is shared state
        benefiting every future request, so one short-budget client
        must not abort it mid-warmup — that would discard the compile
        work and make each budgeted request restart the same doomed
        load.  The triggering request's own budget is still enforced
        by the caller's check right after this returns."""
        model = self.repository.get_model(name)
        if model is None:
            raise ModelNotFound(name)
        if not model.ready:
            with deadline_scope(None):
                await maybe_await(model.load())
        return model

    def wire_dtype_hint(self, name: str) -> Any:
        """The served model's preferred wire dtype (e.g. "u1" for uint8
        image models), handed to the native parser so integer bodies
        land in the model's dtype directly."""
        model = self.repository.get_model(name)
        return getattr(model, "wire_dtype", None)

    def decode_body(self, headers: Dict[str, str], body: bytes,
                    dtype_hint: Any = None) -> Any:
        """Decode a request body: CloudEvent (binary or structured) or JSON.

        Dense numeric V1 bodies take the native tensorjson fast path
        (protocol/native.py): one C pass straight into a contiguous
        array — uint8 when `dtype_hint` says the model takes uint8 and
        the values fit, else int32/float32.  Everything else
        (CloudEvents, V2 tensor objects, dict instances, strings)
        decodes as before.
        """
        if cloudevents.has_ce_headers(headers) or cloudevents.is_structured(headers):
            try:
                return cloudevents.from_http(headers, body)
            except ValueError as e:
                raise InvalidInput(f"Cloud Event Exceptions: {e}")
        header_len = headers.get(v2.INFERENCE_HEADER_CONTENT_LENGTH)
        if header_len is not None:
            # V2 binary data extension: JSON header + raw tensor bytes.
            try:
                return InferRequest.from_binary(body, int(header_len))
            except ValueError as e:
                raise InvalidInput(str(e))
        if body[:1] == b"{" and b'"datatype"' not in body:
            fast = native.parse_v1(body, hint=dtype_hint)
            if fast is not None:
                arr, key = fast
                return {key: arr}
        try:
            return json.loads(body) if body else {}
        except ValueError as e:
            raise InvalidInput(f"Unrecognized request format: {e}")

    async def infer(self, name: str, body: Any) -> Any:
        # Stage-boundary deadline checks (InferLine discipline): a
        # request already over budget after a lazy model load or a
        # slow preprocess fails 504 HERE, before the model/batcher
        # spends a slot on it.
        model = await self.get_model(name)
        # Chaos hook (site `dataplane.infer`, `match` selects models):
        # injected latency/errors land INSIDE the request's measured
        # path, so the SLO engine, flight recorder, and monitors see
        # exactly what a real model-side slowdown would produce —
        # the knob tests/test_monitoring.py drives the alert loop
        # with.  configured() keeps the no-faults hot path at one
        # dict lookup.
        if faults.configured(fault_sites.DATAPLANE_INFER):
            await faults.inject(fault_sites.DATAPLANE_INFER, key=name)
        check_deadline("dataplane.infer")
        with tracer.span("dataplane.preprocess", model=name):
            request = await model.preprocess(body)
        request = self.validate(request)
        check_deadline("dataplane.infer preprocess")
        with tracer.span("dataplane.predict", model=name):
            response = await maybe_await(model.predict(request))
        with tracer.span("dataplane.postprocess", model=name):
            return await model.postprocess(response)

    async def explain(self, name: str, body: Any) -> Any:
        model = await self.get_model(name)
        check_deadline("dataplane.explain")
        request = await model.preprocess(body)
        request = self.validate(request)
        check_deadline("dataplane.explain preprocess")
        response = await maybe_await(model.explain(request))
        return await model.postprocess(response)

    async def generate(self, name: str, body: Any) -> Any:
        model = await self.get_model(name)
        check_deadline("dataplane.generate")
        generate = getattr(model, "generate", None)
        if generate is None:
            raise InvalidInput(
                f"model {name} does not support :generate")
        return await maybe_await(generate(body))

    async def generate_stream(self, name: str, body: Any):
        model = await self.get_model(name)
        stream = getattr(model, "generate_stream", None)
        if stream is None:
            raise InvalidInput(
                f"model {name} does not support streaming generation")
        # Awaiting runs validation + submission NOW: a bad request is a
        # 4xx before any streaming headers are committed.
        return await maybe_await(stream(body))

    def validate(self, request: Any) -> Any:
        if isinstance(request, dict) and "inputs" in request and isinstance(
                request.get("inputs"), list) and request["inputs"] and isinstance(
                request["inputs"][0], dict) and "datatype" in request["inputs"][0]:
            # Looks like a V2 tensor request; structural validation happens
            # in InferRequest.from_dict on the engine side.
            return request
        if isinstance(request, dict):
            return v1.validate_request(request)
        return request

    # -- repository --------------------------------------------------------
    async def load(self, name: str) -> None:
        try:
            ok = await self.repository.load(name)
        except Exception as e:
            raise ModelNotReady(name, f"Error type: {type(e)} error msg: {e}")
        if not ok or not self.repository.is_model_ready(name):
            raise ModelNotReady(name)

    async def unload(self, name: str) -> None:
        try:
            await self.repository.unload(name)
        except KeyError:
            raise ModelNotFound(name)

    def repository_index(self) -> List[Dict[str, Any]]:
        """V2 repository index extension (Triton-style)."""
        return [
            {"name": m.name, "state": "READY" if m.ready else "UNAVAILABLE"}
            for m in self.repository.get_models()
        ]
