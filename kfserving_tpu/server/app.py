"""ModelServer: the TPU-native model server with the V1/V2 route table.

Route table is a superset of the reference server's
(reference python/kfserving/kfserving/kfserver.py:61-87):

    GET  /                                  liveness ("Alive")
    GET  /v2/health/live                    V2 server live
    GET  /v2/health/ready                   V2 server ready (all models)
    GET  /v2                                V2 server metadata
    GET  /v1/models  /v2/models             list models
    GET  /v1/models/{name}                  model health
    GET  /v2/models/{name}                  V2 model metadata
    GET  /v2/models/{name}/status           model health (reference alias)
    GET  /v2/models/{name}/ready            V2 model ready
    POST /v1/models/{name}:predict          V1 predict
    POST /v2/models/{name}/infer            V2 infer
    POST /v1/models/{name}:explain          V1 explain
    POST /v2/models/{name}/explain          V2 explain
    POST /v2/repository/models/{name}/load  load (model repository ext.)
    POST /v2/repository/models/{name}/unload
    GET  /v2/repository/index               repository index
    GET  /metrics                           Prometheus metrics

Unlike the reference (tornado, forked workers, kfserver.py:89-108) this is a
single-process asyncio server: the TPU chip is owned by one runtime, requests
interleave on the event loop, and parallelism comes from batched XLA
execution rather than process forking.
"""

import argparse
import asyncio
import contextlib
import json
import logging
import os
import signal
import time
from typing import Any, Dict, List, Optional

from kfserving_tpu.model.model import Model
from kfserving_tpu.model.repository import ModelRepository
from kfserving_tpu.protocol import cloudevents, native
from kfserving_tpu.protocol.errors import ServingError
from kfserving_tpu.server.dataplane import DataPlane
from kfserving_tpu.server.http import HTTPServer, Request, Response, Router
from kfserving_tpu.server.metrics import Metrics

logger = logging.getLogger("kfserving_tpu.server")

DEFAULT_HTTP_PORT = 8080

# Same CLI surface as the reference parent parser (kfserver.py:34-43) so
# per-framework __main__ modules inherit it.
parser = argparse.ArgumentParser(add_help=False)
parser.add_argument("--http_port", default=DEFAULT_HTTP_PORT, type=int,
                    help="The HTTP port listened to by the model server.")
parser.add_argument("--workers", default=1, type=int,
                    help="Unused; kept for reference CLI compatibility "
                         "(single process owns the TPU).")
parser.add_argument("--max_latency_ms", default=5.0, type=float,
                    help="Dynamic batcher flush deadline in milliseconds.")
parser.add_argument("--max_batch_size", default=32, type=int,
                    help="Dynamic batcher max batch size.")
parser.add_argument("--container_concurrency", default=0, type=int,
                    help="Max concurrent inference calls per replica "
                         "(0 = unlimited; Knative containerConcurrency).")
parser.add_argument("--grpc_port", default=None, type=int,
                    help="V2 gRPC port (unset = gRPC disabled, 0 = "
                         "ephemeral).")


def _json(data: Any, status: int = 200) -> Response:
    fast = native.dump_response(data)
    if fast is not None:
        return Response(fast, status=status)
    return Response(json.dumps(data, default=_np_default).encode("utf-8"),
                    status=status)


def _np_default(obj):
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    raise TypeError(
        f"Object of type {type(obj).__name__} is not JSON serializable")


@contextlib.contextmanager
def _staged(stages: Dict[str, float], stage: str):
    """Record a stage's wall time (ms) into `stages` for the access
    log — one shared helper instead of per-request timer classes."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        stages[stage] = round((time.perf_counter() - t0) * 1000.0, 3)


def _error(e: ServingError) -> Response:
    return _json({"error": e.reason}, status=e.status_code)


class _AdmissionGate:
    """FIFO concurrency gate with a bounded wait queue.

    Not an asyncio.Semaphore: Semaphore.locked() ignores waiters before
    Python 3.12 and acquire() permits barging, which would let newcomers
    starve queued requests and grow the queue past its bound.  This gate
    hands a finishing request's slot directly to the oldest waiter.
    """

    def __init__(self, limit: int, max_queue: int):
        self.limit = limit
        self.max_queue = max_queue
        self.active = 0
        self.queue = []  # FIFO of futures

    async def enter(self) -> bool:
        """True once a slot is held; False = queue full, reject."""
        if self.active < self.limit and not self.queue:
            self.active += 1
            return True
        if len(self.queue) >= self.max_queue:
            return False
        fut = asyncio.get_running_loop().create_future()
        self.queue.append(fut)
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # granted between the cancel and now: pass the slot on
                self.exit()
            else:
                try:
                    self.queue.remove(fut)
                except ValueError:
                    pass
            raise
        return True

    def exit(self) -> None:
        while self.queue:
            fut = self.queue.pop(0)
            if not fut.done():
                fut.set_result(None)  # slot transferred; active unchanged
                return
        self.active -= 1


class ModelServer:
    def __init__(self, http_port: int = DEFAULT_HTTP_PORT,
                 registered_models: Optional[ModelRepository] = None,
                 enable_docs: bool = True,
                 container_concurrency: int = 0,
                 max_queue_depth: Optional[int] = None,
                 grpc_port: Optional[int] = None):
        self.repository = registered_models or ModelRepository()
        self.dataplane = DataPlane(self.repository)
        self.http_port = http_port
        # V2 gRPC front end over the same dataplane (None = disabled;
        # 0 = ephemeral port).
        self.grpc_port = grpc_port
        self.grpc_server = None
        self.metrics = Metrics()
        self.router = Router()
        self._register_routes()
        self.http_server = HTTPServer(self.router)
        self.request_hooks = []  # agent logger taps in here
        # Agent-style background services (logger, watcher, puller): objects
        # with async start()/stop(), run for the server's lifetime.
        self.services = []
        # The online monitoring loop (ISSUE 3): monitor bus tee off the
        # request hooks, SLO burn-rate engine over this server's
        # request series, flight recorder of recent timelines.
        # Construction is cheap (no tasks until start_async); the
        # SLO evaluation loop only runs when objectives are declared.
        from kfserving_tpu.observability.monitoring import Monitoring

        self.monitoring = Monitoring(self)
        self.services.append(self.monitoring)
        # Continuous telemetry history (ISSUE 17): the ring TSDB
        # sampler ticks every registry family — the process-wide
        # REGISTRY plus THIS server's private request registry — into
        # bounded rings, runs the scrape-time publishers so live
        # scrapes and history agree, and feeds the trend detector
        # whose change-points pin into this server's flight recorder.
        # KFS_HISTORY=0 disables the whole subsystem.
        from kfserving_tpu.observability.history import (
            HistorySampler,
            TrendDetector,
            history_enabled,
        )

        self.history: Optional[HistorySampler] = None
        if history_enabled():
            from kfserving_tpu.observability.registry import REGISTRY

            self.history = HistorySampler(
                registries=[self.metrics.registry, REGISTRY],
                fault_hook=self._history_tick_fault,
                publishers=[self.publish_engine_gauges])
            self.history.detector = TrendDetector(
                self.history.store,
                recorder=self.monitoring.flight_recorder)
            self.services.append(self.history)
        # Incident engine (ISSUE 18): the join over every detector —
        # SLO breach edges, trend change-points, sanitizer violations,
        # eviction/fault-back storms, failovers — diagnosed against
        # the additive decomposition with a cross-signal evidence
        # bundle, served at GET /debug/incidents.  Triggers tee off
        # the flight recorder's pin stream and the SLO engine's
        # breach edge; diagnosis runs on a background worker behind
        # the observability.incident_open fault site (injected hook —
        # observability/ never imports reliability/).  KFS_INCIDENTS=0
        # disables the subsystem.
        from kfserving_tpu.observability.incidents import (
            IncidentManager,
            incidents_enabled,
        )

        self.incidents: Optional[IncidentManager] = None
        if incidents_enabled():
            self.incidents = IncidentManager(
                history=(self.history.store
                         if self.history is not None else None),
                recorder=self.monitoring.flight_recorder,
                providers={"cache": self._incident_cache_snapshot},
                fault_hook=self._incident_open_fault)
            self.monitoring.flight_recorder.add_pin_listener(
                self.incidents.on_pin)
            self.monitoring.slo.transition_listeners.append(
                self.incidents.on_slo_transition)
            self.services.append(self.incidents)
        # Per-replica admission control (Knative containerConcurrency,
        # reference component.go:79-82): at most `container_concurrency`
        # inference calls execute at once; up to `max_queue_depth` more
        # wait (the queue-proxy buffer), the rest get 503 so the load
        # balancer retries another replica.  0 = unlimited.
        self.container_concurrency = container_concurrency
        self.max_queue_depth = (
            max_queue_depth if max_queue_depth is not None
            else max(2 * container_concurrency, 8))
        self._admission = (
            _AdmissionGate(container_concurrency, self.max_queue_depth)
            if container_concurrency > 0 else None)
        # Standby: a callable that performs the deferred (device-
        # touching) model load; set via standby_model().
        self._standby_fn = None
        self._standby_state = "none"  # none | armed | activating | done
        # Durable KV handoff (ISSUE 19): single-flight peer pulls —
        # the router's x-kfs-kv-peer retry hint can arrive on many
        # concurrent failover retries at once; one pull per
        # predecessor serves them all (the lock serializes, the set
        # dedups for the process life).
        self._kv_peer_lock = asyncio.Lock()
        self._kv_peers_pulled: set = set()

    def standby_model(self, activate_fn) -> None:
        """Arm standby mode: the server starts with NO model and
        `activate_fn` (blocking; returns the loaded Model) runs on the
        first POST /standby/activate.

        This is the chip-owner recycle fast-path: everything that does
        NOT need the TPU — interpreter start, jax/flax imports, artifact
        download, config parse — happens while the predecessor still
        owns the chip, so the swap gap shrinks to device init + cache-
        hot compile + warmup."""
        self._standby_fn = activate_fn
        self._standby_state = "armed"

    # -- routes ------------------------------------------------------------
    def _register_routes(self):
        r = self.router
        r.add("GET", "/", self._live)
        r.add("GET", "/v2/health/live", self._live)
        r.add("GET", "/v2/health/ready", self._server_ready)
        r.add("GET", "/v2", self._server_metadata)
        r.add("GET", "/v1/models", self._list_models)
        r.add("GET", "/v2/models", self._list_models)
        r.add("GET", "/v1/models/{name}", self._model_health)
        r.add("GET", "/v2/models/{name}/status", self._model_health)
        r.add("GET", "/v2/models/{name}/ready", self._model_ready)
        r.add("GET", "/v2/models/{name}", self._model_metadata)
        r.add("POST", "/v1/models/{name}:predict", self._predict_v1)
        r.add("POST", "/v2/models/{name}/infer", self._infer_v2)
        # Versioned forms (required_api.md:35-56 — the version segment
        # is optional for servers with one live version per name; these
        # accept any version and serve the registered model).
        r.add("GET", "/v2/models/{name}/versions/{version}/ready",
              self._model_ready)
        r.add("GET", "/v2/models/{name}/versions/{version}",
              self._model_metadata)
        r.add("POST", "/v2/models/{name}/versions/{version}/infer",
              self._infer_v2)
        r.add("POST", "/v1/models/{name}:explain", self._explain)
        r.add("POST", "/v2/models/{name}/explain", self._explain)
        # Generative routes (the v2 generate extension; no reference
        # counterpart — its server predates generative serving).  The
        # V1 spelling mirrors :predict; {"stream": true} upgrades to a
        # chunked token stream, as does the dedicated _stream route.
        r.add("POST", "/v1/models/{name}:generate", self._generate)
        r.add("POST", "/v2/models/{name}/generate", self._generate)
        r.add("POST", "/v2/models/{name}/generate_stream",
              self._generate_stream)
        r.add("POST", "/v2/repository/models/{name}/load", self._load)
        r.add("POST", "/v2/repository/models/{name}/unload", self._unload)
        r.add("GET", "/v2/repository/index", self._repository_index)
        r.add("GET", "/metrics", self._metrics)
        # Boot-phase breakdown (VERDICT r4 weak #4): cumulative
        # seconds-since-process-birth marks for interpreter+imports,
        # download, init, compile/warmup, serving — the recycling
        # orchestrator scrapes this to explain successor load time.
        r.add("GET", "/startup_phases", self._startup_phases)
        # Standby activation (recycle fast-swap): a successor process
        # boots with imports/download done but the device untouched;
        # the orchestrator POSTs here once the old chip owner exits.
        r.add("POST", "/standby/activate", self._standby_activate)
        # Durable KV handoff (ISSUE 19): the peer-transfer surface.
        # The chain index, single-chain payload pulls (digest header
        # verified by the receiver), and the re-attach trigger — a
        # bare POST re-scans the persistent tier dir for orphaned
        # predecessor generations; a body naming a peer pulls its
        # resident chains over HTTP instead (the disaggregation
        # substrate ROADMAP item 3 names).
        r.add("GET", "/kv/chains", self._kv_chains)
        r.add("GET", "/kv/chains/{chain}", self._kv_chain_payload)
        r.add("POST", "/kv/reattach", self._kv_reattach)
        # Online monitoring surface (ISSUE 3): SLO health the router
        # federates, and the flight recorder's recent/pinned request
        # timelines.
        r.add("GET", "/v2/health/slo", self._slo_health)
        r.add("GET", "/debug/flightrecorder", self._flightrecorder)
        # Tracing/profiling surface (SURVEY §5.1).
        r.add("GET", "/debug/traces", self._traces)
        r.add("POST", "/debug/profiler/start", self._profiler_start)
        r.add("POST", "/debug/profiler/stop", self._profiler_stop)
        # Device-time observability (ISSUE 6): the engine event
        # timeline as a Chrome-trace/Perfetto download, and a bounded
        # on-demand jax.profiler capture window for TPU-level
        # drill-down (start/sleep/stop in one call — the manual
        # start/stop pair above stays for long captures).
        r.add("GET", "/debug/profile", self._profile)
        r.add("POST", "/debug/profile/capture", self._profile_capture)
        # Cache & cost attribution (ISSUE 13): per-engine prefix-index
        # census + pool/HBM occupancy snapshot, federated by the
        # router under the `replica` label — the feed prefix-affinity
        # routing and the HBM residency manager will read.
        r.add("GET", "/debug/cache", self._cache)
        # Telemetry history (ISSUE 17): the replica's ring-TSDB query
        # surface, federated by the router under the `replica` label
        # with a fleet rollup.
        r.add("GET", "/debug/history", self._history)
        # Incident engine (ISSUE 18): diagnosed incident records —
        # list summaries, ?id= pulls one full record with its
        # evidence bundle, ?state=open filters.  Federated by the
        # router with fleet-level root-cause dedup.
        r.add("GET", "/debug/incidents", self._incidents)

    # -- handlers ----------------------------------------------------------
    async def _live(self, req: Request) -> Response:
        return Response(b"Alive", content_type="text/plain")

    async def _server_ready(self, req: Request) -> Response:
        ready = self.dataplane.server_ready()
        body = {"ready": ready}
        from kfserving_tpu.reliability import sanitizer

        if sanitizer.enabled():
            # Sanitize runs surface their discipline state where the
            # probe already looks: armed sources, violation counts
            # (all zero = the clean bill the smoke gate asserts).
            body["sanitizer"] = sanitizer.status()
        return _json(body, status=200 if ready else 503)

    async def _server_metadata(self, req: Request) -> Response:
        return _json(self.dataplane.server_metadata())

    async def _list_models(self, req: Request) -> Response:
        return _json(self.dataplane.list_models())

    async def _model_health(self, req: Request) -> Response:
        name = req.path_params["name"]
        try:
            model = self.dataplane.model_ready(name)
        except ServingError as e:
            return _error(e)
        return _json({"name": model.name, "ready": model.ready})

    async def _model_ready(self, req: Request) -> Response:
        try:
            self.dataplane.model_ready(req.path_params["name"])
        except ServingError as e:
            return _error(e)
        return Response(b"", status=200)

    async def _model_metadata(self, req: Request) -> Response:
        try:
            return _json(self.dataplane.model_metadata(req.path_params["name"]))
        except ServingError as e:
            return _error(e)

    async def _predict_v1(self, req: Request) -> Response:
        return await self._inference(req, "predict", self.dataplane.infer)

    async def _infer_v2(self, req: Request) -> Response:
        return await self._inference(req, "infer", self.dataplane.infer)

    async def _explain(self, req: Request) -> Response:
        return await self._inference(req, "explain", self.dataplane.explain)

    async def _inference(self, req: Request, verb: str, op) -> Response:
        from kfserving_tpu.reliability import Deadline
        from kfserving_tpu.tracing import (
            REQUEST_ID_HEADER,
            ensure_request_id,
        )

        name = req.path_params["name"]
        rid = ensure_request_id(req.headers)
        # Per-request budget (x-request-timeout-ms): minted here at
        # ingress, carried by contextvar through dataplane, batcher
        # queue, and engine dispatch — each stage sheds the request
        # with 504 the moment the budget is spent instead of wasting
        # device work on an answer nobody is waiting for.
        deadline = Deadline.from_headers(req.headers)
        start = time.perf_counter()
        if self._admission is not None:
            admitted = await self._enter_admission(deadline)
            if admitted is not True:
                status, error = self._shed_reason(admitted)
                latency_ms = (time.perf_counter() - start) * 1000.0
                resp = _json({"error": error}, status=status)
                self.metrics.observe_request(name, verb, status,
                                             latency_ms,
                                             trace_id=rid)
                # A shed is exactly what the flight recorder exists to
                # keep evidence of (504 pins as deadline_shed).
                self.monitoring.record_request(name, verb, status,
                                               latency_ms,
                                               trace_id=rid)
                # Shed requests still reach the hooks: the payload logger
                # must not go blind exactly during overload.
                for hook in self.request_hooks:
                    try:
                        hook(name, verb, req, resp, latency_ms)
                    except Exception:
                        logger.exception("request hook failed")
                resp.headers[REQUEST_ID_HEADER] = rid
                return resp
            try:
                resp = await self._inference_inner(
                    req, verb, op, name, start, deadline,
                    trace_id=rid)
            finally:
                self._admission.exit()
        else:
            resp = await self._inference_inner(req, verb, op, name,
                                               start, deadline,
                                               trace_id=rid)
        resp.headers[REQUEST_ID_HEADER] = rid
        return resp

    @staticmethod
    def _shed_reason(admitted: Optional[bool]):
        """Status + message for a failed admission.  False: queue full
        (503, the load balancer retries elsewhere).  None: the budget
        died while queued — 504 without ever holding a slot, so an
        engine batch slot is never consumed for it."""
        if admitted is False:
            return 503, "concurrency limit exceeded"
        return 504, "request deadline exceeded (admission queue)"

    async def _enter_admission(self, deadline) -> Optional[bool]:
        """Admission with a budget-bounded queue wait: True = slot
        held, False = queue full (503), None = deadline expired while
        queued (504).  wait_for's cancellation is safe against the
        grant race: _AdmissionGate.enter() hands an already-granted
        slot to the next waiter when cancelled."""
        if deadline is None:
            return await self._admission.enter()
        remaining = deadline.remaining_s()
        if remaining <= 0:
            return None
        try:
            return await asyncio.wait_for(self._admission.enter(),
                                          timeout=remaining)
        except asyncio.TimeoutError:
            return None

    async def _inference_inner(self, req: Request, verb: str, op,
                               name: str, start: float,
                               deadline=None,
                               trace_id: Optional[str] = None
                               ) -> Response:
        from kfserving_tpu.observability.accesslog import log_access
        from kfserving_tpu.reliability import deadline_scope
        from kfserving_tpu.tracing import tracer

        status = 200
        stages: Dict[str, float] = {}
        tokens_out = None
        try:
            if deadline is not None and deadline.expired:
                # Budget spent waiting for the admission slot: 504
                # without touching decode or the engine (the slot is
                # released by the caller's finally).
                from kfserving_tpu.reliability import DeadlineExceeded

                raise DeadlineExceeded("admission queue")
            with deadline_scope(deadline):
                with tracer.span("server.decode", model=name,
                                 verb=verb), _staged(stages, "decode"):
                    body = self.dataplane.decode_body(
                        req.headers, req.body,
                        dtype_hint=self.dataplane.wire_dtype_hint(name))
                with tracer.span("server.infer", model=name,
                                 verb=verb), _staged(stages, "infer"):
                    response = await op(name, body)
                with tracer.span("server.encode", model=name,
                                 verb=verb), _staged(stages, "encode"):
                    resp = self._encode_response(req, body, response)
                if isinstance(response, dict):
                    tokens_out = response.get("details", {}).get(
                        "token_count") if isinstance(
                            response.get("details"), dict) else None
        except ServingError as e:
            status = e.status_code
            resp = _error(e)
        except Exception as e:
            logger.exception("%s failed for model %s", verb, name)
            status = 500
            resp = _json({"error": str(e)}, status=500)
        latency_ms = (time.perf_counter() - start) * 1000.0
        self.metrics.observe_request(name, verb, status, latency_ms,
                                     trace_id=trace_id)
        # Flight-recorder capture AFTER the stage spans completed (the
        # tracer ring already holds this trace's batcher/engine spans)
        # and BEFORE the hooks, so a slow hook can't delay pin
        # evaluation past the next request.
        self.monitoring.record_request(name, verb, status, latency_ms,
                                       trace_id=trace_id,
                                       stages=stages or None)
        from kfserving_tpu.observability import attribution

        log_access("server", trace_id=trace_id, model=name, verb=verb,
                   status=status, latency_ms=round(latency_ms, 3),
                   stages=stages or None, tokens_out=tokens_out,
                   cost=attribution.lookup(trace_id))
        for hook in self.request_hooks:
            try:
                hook(name, verb, req, resp, latency_ms)
            except Exception:
                logger.exception("request hook failed")
        return resp

    def _encode_response(self, req: Request, body: Any, response: Any
                         ) -> Response:
        """Echo CloudEvents framing when the request was a CloudEvent
        (reference handlers/http.py:96-109); binary-extension responses
        when the V2 request asked for binary_data_output."""
        if isinstance(body, cloudevents.CloudEvent):
            event = cloudevents.CloudEvent(body.attributes, response)
            if cloudevents.is_structured(req.headers):
                headers, payload = cloudevents.to_structured(event)
            else:
                headers, payload = cloudevents.to_binary(event)
            return Response(payload, headers=headers)
        from kfserving_tpu.protocol.v2 import (
            InferRequest,
            encode_binary_response,
        )

        if (isinstance(body, InferRequest)
                and body.parameters.get("binary_data_output")
                and isinstance(response, dict)
                and response.get("outputs")):
            payload, hlen = encode_binary_response(response)
            return Response(
                payload,
                headers={
                    "content-type": "application/octet-stream",
                    "inference-header-content-length": str(hlen)})
        return _json(response)

    async def _generate(self, req: Request) -> Response:
        # Failover fetch hint (ISSUE 19): warm the tier from the
        # predecessor before dispatch — one single-flight pull per
        # peer; the set probe makes the steady-state cost zero.
        await self._maybe_peer_import(req.headers,
                                      req.path_params["name"])
        # Cheap pre-scan avoids a duplicate json.loads on the hot
        # non-streaming path (_inference decodes the body itself).
        if b'"stream"' in req.body:
            try:
                body = json.loads(req.body) if req.body else {}
            except ValueError:
                return _json({"error": "malformed JSON body"},
                             status=400)
            if isinstance(body, dict) and body.get("stream"):
                return await self._generate_stream(req, body=body)
        return await self._inference(req, "generate",
                                     self.dataplane.generate)

    async def _generate_stream(self, req: Request,
                               body: Any = None) -> Response:
        from kfserving_tpu.server.http import StreamingResponse
        from kfserving_tpu.tracing import (
            REQUEST_ID_HEADER,
            ensure_request_id,
        )

        name = req.path_params["name"]
        await self._maybe_peer_import(req.headers, name)
        rid = ensure_request_id(req.headers)
        # Budget applies to submission AND rides into the engine
        # request (captured at submit): a stream whose budget expires
        # mid-generation finishes with reason "timeout" instead of
        # holding its decode slot to the token budget.
        from kfserving_tpu.reliability import Deadline, deadline_scope

        deadline = Deadline.from_headers(req.headers)
        if body is None:
            try:
                body = json.loads(req.body) if req.body else {}
            except ValueError:
                return _json({"error": "malformed JSON body"},
                             status=400)
        # Streams go through the SAME admission gate as every other
        # inference verb — they are the longest-lived, slot-holding
        # requests in the system, exactly what containerConcurrency
        # exists to bound.  The slot is held until the stream ends
        # (released in sse()'s finally, since the body outlives this
        # handler).
        gated = False
        if self._admission is not None:
            admitted = await self._enter_admission(deadline)
            if admitted is not True:
                status, error = self._shed_reason(admitted)
                resp = _json({"error": error}, status=status)
                self.metrics.observe_request(name, "generate_stream",
                                             status, 0.0,
                                             trace_id=rid)
                resp.headers[REQUEST_ID_HEADER] = rid
                return resp
            gated = True
        try:
            with deadline_scope(deadline):
                events = await self.dataplane.generate_stream(name, body)
        except ServingError as e:
            if gated:
                self._admission.exit()
            resp = _error(e)
            resp.headers[REQUEST_ID_HEADER] = rid
            return resp
        except Exception:
            if gated:
                self._admission.exit()
            raise
        start = time.perf_counter()
        metrics, hooks = self.metrics, self.request_hooks
        admission = self._admission if gated else None
        state = {"status": 200}

        async def sse():
            try:
                async for event in events:
                    payload = json.dumps(event, default=_np_default)
                    yield f"data: {payload}\n\n".encode("utf-8")
            except Exception:
                logger.exception("generate stream for %s failed", name)
                state["status"] = 500
                raise

        async def on_close():
            # Runs exactly once on every exit path — including a
            # client that disconnected before the body was ever
            # iterated (a plain generator's finally never runs there,
            # which used to leak the containerConcurrency slot per
            # disconnect until the server wedged at all-503).
            if admission is not None:
                admission.exit()
            # Propagate the close to the model's event stream so the
            # engine frees the decode slot on abandonment.
            from kfserving_tpu.streams import aclose_quietly

            await aclose_quietly(events, "model event stream")
            latency_ms = (time.perf_counter() - start) * 1000.0
            metrics.observe_request(name, "generate_stream",
                                    state["status"], latency_ms,
                                    trace_id=rid)
            # Streams are flight-recorded at close: their generator
            # span (tokens, finish reason) exists only once the
            # stream ends.
            self.monitoring.record_request(name, "generate_stream",
                                           state["status"],
                                           latency_ms, trace_id=rid)
            from kfserving_tpu.observability import attribution
            from kfserving_tpu.observability.accesslog import (
                log_access,
            )

            # The stream's cost record exists by now: the engine
            # finalizes it at the terminal event, and on_close runs
            # after the event stream ended (or was abandoned — the
            # cancel path finalizes too).
            log_access("server", trace_id=rid, model=name,
                       verb="generate_stream",
                       status=state["status"],
                       latency_ms=round(latency_ms, 3),
                       cost=attribution.lookup(rid))
            # Hooks get a minimal response carrying the stream's REAL
            # outcome: a mid-stream failure must not reach the payload
            # logger / monitor bus stamped as a 200.  The body is
            # empty — the token stream was never buffered.
            stream_resp = Response(b"", status=state["status"])
            for hook in hooks:
                try:
                    hook(name, "generate_stream", req, stream_resp,
                         latency_ms)
                except Exception:
                    logger.exception("request hook failed")

        from kfserving_tpu.streams import GuardedStream

        return StreamingResponse(GuardedStream(sse(), on_close),
                                 headers={REQUEST_ID_HEADER: rid})

    async def _standby_activate(self, req: Request) -> Response:
        from kfserving_tpu import startup

        if self._standby_fn is None:
            return _json({"error": "server is not in standby mode"},
                         status=409)
        if self._standby_state == "done":
            return _json({"activated": True, "already": True})
        if self._standby_state == "activating":
            return _json({"error": "activation already in progress"},
                         status=409)
        self._standby_state = "activating"
        t0 = time.perf_counter()
        try:
            model = await asyncio.get_running_loop().run_in_executor(
                None, self._standby_fn)
            self.register_model(model)
            self._standby_state = "done"
        except Exception as e:
            self._standby_state = "armed"  # retryable
            logger.exception("standby activation failed")
            return _json({"error": f"activation failed: {e}"},
                         status=500)
        # kfslint: disable=async-blocking — mark()'s /proc read is
        # RAM-backed and runs once per process (birth time cached).
        startup.mark("standby_activate")
        # The orchestrator's swap breakdown attaches this: how long
        # the device-touching half took, and whether params came off
        # the mmap cache ("mmap") or paid full materialization.
        return _json({
            "activated": True, "model": model.name,
            "activate_s": round(time.perf_counter() - t0, 3),
            "param_source": getattr(model, "param_source", None),
            "phases": startup.phases(),
        })

    # -- durable KV handoff (ISSUE 19) -------------------------------------
    def _kv_tier_models(self, name: Optional[str] = None):
        """(model, engine, tier) triples for every registered model
        with a host KV tier (optionally filtered by model name)."""
        out = []
        for model in self.repository.get_models():
            if name is not None and model.name != name:
                continue
            engine = getattr(model, "engine", None)
            tier = getattr(engine, "kv_tier", None)
            if tier is not None:
                out.append((model, engine, tier))
        return out

    async def _kv_chains(self, req: Request) -> Response:
        """Peer-transfer index: every host-tier-resident chain digest
        per model, with the block geometry a puller needs to validate
        compatibility before moving payload bytes."""
        name = req.query.get("model")
        models: Dict[str, Any] = {}
        for model, _engine, tier in self._kv_tier_models(name):
            models[model.name] = {
                "block_bytes": tier.block_bytes,
                "chains": tier.chains(),
            }
        return _json({"models": models})

    async def _kv_chain_payload(self, req: Request) -> Response:
        """One chain's block payload, streamed to a pulling peer.
        The digest header lets the receiver verify the bytes before
        admission — a corrupted transfer is discarded there, never
        served."""
        from kfserving_tpu.engine.kv_tier import payload_digest

        chain_hex = req.path_params["chain"]
        try:
            chain = bytes.fromhex(chain_hex)
        except ValueError:
            return _json({"error": "chain must be a hex digest"},
                         status=400)
        name = req.query.get("model")
        loop = asyncio.get_running_loop()
        for model, _engine, tier in self._kv_tier_models(name):
            try:
                # Off-loop: the read copies one block's bytes out of
                # the tier mmap under its lock.
                payload = await loop.run_in_executor(
                    None, tier.read, chain)
            except KeyError:
                continue
            return Response(
                payload,
                headers={
                    "content-type": "application/octet-stream",
                    "x-kfs-kv-digest": payload_digest(payload),
                    "x-kfs-kv-block-bytes": str(tier.block_bytes),
                    "x-kfs-kv-model": model.name,
                })
        return _json({"error": f"chain {chain_hex} is not resident"},
                     status=404)

    async def _kv_reattach(self, req: Request) -> Response:
        """Re-attach conversation KV after a process boundary.  A
        bare POST re-scans the persistent tier dir and adopts any
        orphaned predecessor generation (digest-verified, per-entry);
        a body naming a `peer` base URL pulls that replica's resident
        chains over /kv/chains instead — the crash-failover path,
        where the predecessor's host died but a surviving replica
        still holds the conversation's blocks."""
        body: Dict[str, Any] = {}
        if req.body:
            try:
                parsed = json.loads(req.body)
                if isinstance(parsed, dict):
                    body = parsed
            except ValueError:
                return _json({"error": "malformed JSON body"},
                             status=400)
        peer = body.get("peer")
        name = body.get("model")
        try:
            budget_s = float(body.get(
                "budget_s",
                os.environ.get("KFS_KV_PEER_BUDGET_S", "2")))
        except (TypeError, ValueError):
            budget_s = 2.0
        if peer:
            results = await self._kv_pull_peer(
                str(peer).rstrip("/"), budget_s, name=name)
            return _json({"peer": peer, "models": results})
        loop = asyncio.get_running_loop()
        results = {}
        for model, _engine, tier in self._kv_tier_models(name):
            try:
                results[model.name] = await loop.run_in_executor(
                    None, tier.reattach)
            except Exception as e:
                logger.exception("kv reattach for %s failed",
                                 model.name)
                results[model.name] = {"error": str(e)}
        if results:
            self.monitoring.flight_recorder.record(
                {"kind": "kv_handoff_reattach", "models": results},
                pin="kv_handoff_reattach")
        return _json({"models": results})

    async def _kv_pull_peer(self, peer: str, budget_s: float,
                            name: Optional[str] = None
                            ) -> Dict[str, Any]:
        """Pull a peer's resident chains into the local tier:
        index fetch, per-chain payload pulls digest-verified on
        receipt, then one transactional engine.kv_import per model.
        Bounded by `budget_s` — a slow peer costs the returning
        conversation a re-prefill, never a stalled request."""
        from kfserving_tpu.observability import metrics as obs
        from kfserving_tpu.engine.kv_tier import payload_digest

        import aiohttp

        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.1, budget_s)
        results: Dict[str, Any] = {}
        timeout = aiohttp.ClientTimeout(total=max(0.1, budget_s))
        try:
            async with aiohttp.ClientSession(timeout=timeout) as s:
                async with s.get(f"{peer}/kv/chains") as resp:
                    if resp.status != 200:
                        return {"error": f"peer index {resp.status}"}
                    index = await resp.json()
                for mname, info in (index.get("models")
                                    or {}).items():
                    if name is not None and mname != name:
                        continue
                    triples = self._kv_tier_models(mname)
                    if not triples:
                        continue
                    _model, engine, tier = triples[0]
                    if info.get("block_bytes") != tier.block_bytes:
                        results[mname] = {
                            "error": "block geometry mismatch"}
                        continue
                    pairs = []
                    mismatches = 0
                    failed = 0
                    for ch_hex in info.get("chains") or []:
                        if loop.time() >= deadline:
                            break
                        try:
                            chain = bytes.fromhex(ch_hex)
                        except ValueError:
                            continue
                        if tier.contains(chain):
                            continue
                        try:
                            async with s.get(
                                    f"{peer}/kv/chains/{ch_hex}",
                                    params={"model": mname}) as r:
                                if r.status != 200:
                                    failed += 1
                                    continue
                                payload = await r.read()
                                want = r.headers.get(
                                    "x-kfs-kv-digest")
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError):
                            failed += 1
                            continue
                        if len(payload) != tier.block_bytes or (
                                want and payload_digest(payload)
                                != want):
                            # Wire corruption: discard, never admit.
                            mismatches += 1
                            continue
                        pairs.append((chain, payload))
                    res = dict(await loop.run_in_executor(
                        None, engine.kv_import, pairs))
                    if mismatches:
                        res["digest_mismatch"] = mismatches
                        obs.kv_handoff_peer_blocks_total().labels(
                            model=mname,
                            outcome="digest_mismatch").inc(
                                mismatches)
                    if failed:
                        res["failed"] = res.get("failed", 0) + failed
                        obs.kv_handoff_peer_blocks_total().labels(
                            model=mname, outcome="failed").inc(
                                failed)
                    results[mname] = res
        except (aiohttp.ClientError, asyncio.TimeoutError,
                OSError) as e:
            results.setdefault("error", f"peer pull failed: {e!r}")
        if results:
            self.monitoring.flight_recorder.record(
                {"kind": "kv_handoff_peer_pull", "peer": peer,
                 "models": {k: v for k, v in results.items()
                            if isinstance(v, dict)}},
                pin="kv_handoff_peer_pull")
        return results

    async def _maybe_peer_import(self, headers: Dict[str, str],
                                 name: str) -> None:
        """Honor the router's failover fetch hint: an x-kfs-kv-peer
        header names the predecessor replica this request was retried
        away from.  One bounded single-flight pull per peer warms the
        local tier before dispatch; any failure degrades to a plain
        re-prefill — the request itself never fails on the hint."""
        peer = None
        for k, v in headers.items():
            if k.lower() == "x-kfs-kv-peer":
                peer = v.strip()
                break
        if not peer:
            return
        peer = peer.rstrip("/")
        if peer in self._kv_peers_pulled:
            return
        if not self._kv_tier_models(name):
            return
        async with self._kv_peer_lock:
            if peer in self._kv_peers_pulled:
                return
            self._kv_peers_pulled.add(peer)
            try:
                budget = float(os.environ.get(
                    "KFS_KV_PEER_BUDGET_S", "2"))
            except ValueError:
                budget = 2.0
            if budget <= 0:
                return
            try:
                await self._kv_pull_peer(peer, budget, name=name)
            except Exception:
                logger.exception("kv peer pull from %s failed", peer)

    async def export_kv(self, budget_s: Optional[float] = None
                        ) -> Dict[str, Any]:
        """Drain parachute: export every engine's live-slot and hot
        prefix-chain KV into its PERSISTENT host tier (ephemeral
        tiers die with the process — exporting into one would be
        theater).  Runs on the SIGTERM drain path between drain()
        and stop_async(), bounded by KFS_KV_EXPORT_BUDGET_S so it
        can never stretch the orchestrator's swap window; 0
        disables."""
        if budget_s is None:
            try:
                budget_s = float(os.environ.get(
                    "KFS_KV_EXPORT_BUDGET_S", "2"))
            except ValueError:
                budget_s = 2.0
        results: Dict[str, Any] = {}
        if budget_s <= 0:
            return results
        loop = asyncio.get_running_loop()
        for model, engine, tier in self._kv_tier_models():
            fn = getattr(engine, "export_kv", None)
            if fn is None or not getattr(tier, "persistent", False):
                continue
            try:
                res = await loop.run_in_executor(None, fn, budget_s)
            except Exception:
                logger.exception("kv export for %s failed",
                                 model.name)
                continue
            results[model.name] = res
        if results:
            self.monitoring.flight_recorder.record(
                {"kind": "kv_handoff_export", "budget_s": budget_s,
                 "models": results},
                pin="kv_handoff_export")
        return results

    async def _load(self, req: Request) -> Response:
        name = req.path_params["name"]
        try:
            await self.dataplane.load(name)
        except ServingError as e:
            return _error(e)
        return _json({"name": name, "load": True})

    async def _unload(self, req: Request) -> Response:
        name = req.path_params["name"]
        try:
            await self.dataplane.unload(name)
        except ServingError as e:
            return _error(e)
        return _json({"name": name, "unload": True})

    async def _repository_index(self, req: Request) -> Response:
        return _json(self.dataplane.repository_index())

    async def _startup_phases(self, req: Request) -> Response:
        from kfserving_tpu import startup

        return _json(startup.phases())

    def publish_engine_gauges(self) -> None:
        """Refresh every scrape-time-published family (roofline MFU /
        padding / goodput / HBM bandwidth, pool occupancy and
        fragmentation ratios, generic per-key engine gauges) from the
        engines' stats dicts.  Runs at every `/metrics` scrape AND on
        the history sampler's tick — before ISSUE 17 these families
        were invisible between scrapes, so history and a live scrape
        could disagree about the same series."""
        from kfserving_tpu.observability.profiling import roofline

        for model in self.repository.get_models():
            engine_stats = getattr(model, "engine_stats", None)
            if engine_stats is None:
                continue
            try:
                stats = engine_stats()
                # Roofline families (MFU, padding-waste, goodput, HBM
                # bandwidth) publish into the process registry, where
                # the router federates them under a `replica` label;
                # consumed keys skip the generic per-key export below
                # so the merged exposition declares each family
                # exactly once.  The cache publisher adds the paged
                # pool's occupancy/fragmentation `_ratio` gauges
                # (ISSUE 13) without consuming the legacy
                # `kfserving_tpu_engine_paged{bucket=...}` export.
                consumed = roofline.publish_gauges(model.name, stats)
                from kfserving_tpu.observability import attribution

                consumed |= attribution.publish_cache_gauges(
                    model.name, stats)
                for key, value in stats.items():
                    if key in consumed:
                        continue
                    if isinstance(value, dict):
                        # Per-bucket stats (bucket_hits/..._pad_waste)
                        # export as labeled series.
                        for bucket, v in value.items():
                            if isinstance(v, (int, float)):
                                self.metrics.set_gauge(
                                    f"kfserving_tpu_engine_{key}",
                                    float(v),
                                    labels={"model": model.name,
                                            "bucket": str(bucket)})
                        continue
                    if isinstance(value, (int, float)):
                        self.metrics.set_gauge(
                            f"kfserving_tpu_engine_{key}", float(value),
                            labels={"model": model.name})
            except Exception:
                logger.exception("engine stats for %s failed", model.name)

    async def _metrics(self, req: Request) -> Response:
        # Engine gauges (device/host breakdown, MFU) refresh at scrape.
        self.publish_engine_gauges()
        # Content negotiation: exemplars are only legal under the
        # OpenMetrics content type; the classic text parser would
        # reject the suffix and drop the whole scrape.
        want_om = "application/openmetrics-text" in \
            req.headers.get("accept", "")
        body = self.metrics.render(exemplars=want_om)
        if want_om:
            body += "# EOF\n"
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8")
        else:
            ctype = "text/plain; version=0.0.4"
        return Response(body.encode("utf-8"), content_type=ctype)

    async def _slo_health(self, req: Request) -> Response:
        """The SLO engine's last evaluation.  ?refresh=1 forces a
        fresh tick (tests / on-demand checks); the body always
        answers 200 — a breach is a *reported* state, not an endpoint
        failure (the router must still federate it)."""
        if req.query.get("refresh") == "1":
            return _json(self.monitoring.slo.tick())
        return _json(self.monitoring.slo.report())

    async def _flightrecorder(self, req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", "100"))
        except ValueError:
            return _json({"error": "limit must be an integer"},
                         status=400)
        pinned_only = req.query.get("pinned", "0") == "1"
        # Pin-stream filters (ISSUE 18): ?pin_type= prefix-matches the
        # pin reason (trend / slo_ / sanitizer_...), ?since_ts= keeps
        # entries stamped at or after the wall-clock time — incident
        # bundles and humans pull just the detector evidence instead
        # of the whole ring.
        pin_type = req.query.get("pin_type") or None
        since_raw = req.query.get("since_ts")
        try:
            since_ts = float(since_raw) if since_raw else None
        except ValueError:
            return _json({"error": "since_ts must be a number"},
                         status=400)
        return _json(self.monitoring.dump_flightrecorder(
            limit=limit, pinned_only=pinned_only, pin_type=pin_type,
            since_ts=since_ts))

    async def _traces(self, req: Request) -> Response:
        from kfserving_tpu.tracing import tracer

        trace_id = req.query.get("trace_id")
        try:
            limit = int(req.query.get("limit", "100"))
        except ValueError:
            return _json({"error": "limit must be an integer"},
                         status=400)
        return _json({"spans": tracer.spans(trace_id, limit)})

    async def _profile(self, req: Request) -> Response:
        """The engine event timeline (decode waves, prefill chunks,
        preemptions, HOLD windows, device dispatch spans) rendered as
        Chrome-trace JSON — loadable directly in Perfetto.
        ?window_s= trims to the trailing window; ?format=events
        returns the raw event dicts instead."""
        from kfserving_tpu.observability.profiling import (
            TIMELINE,
            to_chrome_trace,
        )

        window = req.query.get("window_s")
        try:
            window_s = float(window) if window else None
        except ValueError:
            return _json({"error": "window_s must be a number"},
                         status=400)
        fmt = req.query.get("format", "trace_json")
        if fmt not in ("trace_json", "events"):
            return _json(
                {"error": "format must be trace_json or events"},
                status=400)
        events = TIMELINE.snapshot(window_s)
        if fmt == "events":
            return _json({
                "events": [TIMELINE.event_dict(e) for e in events],
                "recorded": TIMELINE.recorded,
            })
        return _json(to_chrome_trace(events))

    async def _profile_capture(self, req: Request) -> Response:
        """Bounded on-demand jax.profiler capture: start a TPU-level
        trace, hold it for duration_s (clamped to 60 s), stop, return
        the log dir.  409 while another capture (or a manual
        /debug/profiler/start) is active."""
        from kfserving_tpu.tracing import profiler

        try:
            body = json.loads(req.body) if req.body else {}
        except ValueError:
            body = {}
        try:
            duration_s = float(body.get("duration_s", 2.0))
        except (TypeError, ValueError):
            return _json({"error": "duration_s must be a number"},
                         status=400)
        duration_s = max(0.1, min(duration_s, 60.0))
        log_dir = body.get("log_dir", "/tmp/kfs-profile")
        try:
            started = profiler.start(log_dir)
        except Exception as e:
            return _json({"error": f"profiler start failed: {e}"},
                         status=500)
        if not started:
            return _json({"error": "profiler already active",
                          "log_dir": profiler.active_dir}, status=409)
        try:
            await asyncio.sleep(duration_s)
        finally:
            profiler.stop()
        return _json({"captured": True, "log_dir": log_dir,
                      "duration_s": duration_s})

    async def _cache(self, req: Request) -> Response:
        """Replica cache snapshot: per generative model the prefix-
        index entry count, reuse-depth distribution, top-K hot chains
        by hit count, and the pool occupancy stats; plus the HBM
        accountant's residency ledger when one is wired.  ?top_k=
        bounds the hot-chain list (default 10); ?top_cost=K appends
        the attribution ring's top-K cost records (by device-ms and
        by held blocks — `kfs cache --top-cost`)."""
        try:
            top_k = int(req.query.get("top_k", "10"))
            top_cost = int(req.query.get("top_cost", "0"))
        except ValueError:
            return _json({"error": "top_k and top_cost must be "
                                   "integers"}, status=400)
        body = self.cache_snapshot(top_k=top_k)
        if top_cost > 0:
            from kfserving_tpu.observability import attribution

            window_raw = req.query.get("cost_window_s")
            try:
                window_s = float(window_raw) if window_raw else None
            except ValueError:
                return _json({"error": "cost_window_s must be a "
                                       "number"}, status=400)
            body["top_cost"] = {
                "by_device_ms": attribution.top(
                    top_cost, window_s=window_s, by="device_ms"),
                "by_held_blocks": attribution.top(
                    top_cost, window_s=window_s, by="held_blocks"),
            }
        return _json(body)

    def cache_snapshot(self, top_k: int = 10) -> Dict[str, Any]:
        """The /debug/cache body as a plain dict — shared by the
        handler and the incident engine's evidence provider (the
        bundle embeds exactly what the debug endpoint would have
        shown at open time)."""
        models: Dict[str, Any] = {}
        hbm = None
        residency = None
        host_tier: Dict[str, Any] = {}
        seen_managers = set()
        res_manager = getattr(self.repository, "residency", None)
        if res_manager is not None:
            try:
                # Demand-paged residency snapshot (states, fault-in
                # p50/p99, eviction/skip totals) beside the HBM ledger
                # it acts on — one scrape answers "who is resident,
                # how fast do faults land, is anything thrashing".
                residency = res_manager.debug()
            except Exception:
                logger.exception("residency debug failed")
        for model in self.repository.get_models():
            debug = getattr(getattr(model, "engine", None),
                            "cache_debug", None)
            if debug is not None:
                try:
                    models[model.name] = debug(top_k=top_k)
                except Exception:
                    logger.exception("cache debug for %s failed",
                                     model.name)
            tier = getattr(getattr(model, "engine", None),
                           "kv_tier", None)
            if tier is not None:
                try:
                    # Host KV tier beside the device pool it backs:
                    # occupancy, spill/fault-back outcomes, fault-back
                    # latency p50/p99 (ISSUE 16).
                    host_tier[model.name] = tier.debug()
                except Exception:
                    logger.exception("kv tier debug for %s failed",
                                     model.name)
            manager = getattr(model, "hbm", None)
            if manager is not None and id(manager) not in seen_managers:
                seen_managers.add(id(manager))
                try:
                    # One manager per device in practice; a second one
                    # (multi-mesh) appends its ledger.
                    snap = manager.debug()
                    if hbm is None:
                        hbm = snap
                    else:
                        hbm["resident"] += snap["resident"]
                        hbm["used_bytes"] += snap["used_bytes"]
                except Exception:
                    logger.exception("hbm debug failed")
        return {"models": models, "hbm": hbm,
                "residency": residency,
                "host_tier": host_tier or None}

    def _incident_cache_snapshot(self) -> Dict[str, Any]:
        """Evidence-bundle provider: the cache/residency/HBM state at
        incident-open time (bounded hot-chain census)."""
        return self.cache_snapshot(top_k=5)

    async def _incidents(self, req: Request) -> Response:
        """Diagnosed incident records (ISSUE 18).  `?id=` returns one
        full record, evidence bundle and ranked hypotheses included;
        the bare list returns newest-first summaries (`?state=open`
        filters, `?limit=` bounds).  Incidents off (KFS_INCIDENTS=0)
        answers 200 with `enabled: false` — the router must still
        federate the replica."""
        if self.incidents is None:
            return _json({"enabled": False, "open": 0,
                          "incidents": []})
        incident_id = req.query.get("id")
        if incident_id:
            record = self.incidents.get(incident_id)
            if record is None:
                return _json(
                    {"error": f"unknown incident {incident_id}"},
                    status=404)
            return _json(record)
        try:
            limit = int(req.query.get("limit", "50"))
        except ValueError:
            return _json({"error": "limit must be an integer"},
                         status=400)
        state = req.query.get("state") or None
        return _json(self.incidents.report(state=state, limit=limit))

    async def _incident_open_fault(self) -> None:
        """The incident worker's chaos seam: probes the
        `observability.incident_open` fault site before each queued
        trigger is diagnosed.  Lives HERE (not in observability/) so
        the incidents package never imports the reliability layer —
        the hook is injected at construction."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import faults

        await faults.inject(fault_sites.OBSERVABILITY_INCIDENT_OPEN)

    async def _history_tick_fault(self) -> None:
        """The history sampler's chaos seam: probes the
        `observability.history_tick` fault site before every tick.
        Lives HERE (not in observability/) so the history package
        never imports the reliability layer — the hook is injected
        at construction."""
        from kfserving_tpu.reliability import fault_sites
        from kfserving_tpu.reliability.faults import faults

        await faults.inject(fault_sites.OBSERVABILITY_HISTORY_TICK)

    async def _history(self, req: Request) -> Response:
        """Replica telemetry history: aligned (ts, value) frames from
        the in-process ring TSDB.  `?series=` selects one family
        (omitted = every live series), `?labels=k=v,k2=v2` filters by
        label subset, `?window_s=` bounds the lookback (default
        600 s), `?step_s=` resamples onto an absolute epoch grid so
        the router can merge replicas by timestamp.  `?index=1`
        returns the series catalog instead of frames.  History off
        (KFS_HISTORY=0) answers 200 with `enabled: false` — the
        router must still federate the replica."""
        if self.history is None:
            return _json({"enabled": False, "series": []})
        if req.query.get("index") == "1":
            return _json({"enabled": True,
                          "tick_s": self.history.tick_s,
                          "tiers": self.history.store.tiers,
                          "series": self.history.store.index()})
        series = req.query.get("series") or None
        labels: Dict[str, str] = {}
        for pair in (req.query.get("labels") or "").split(","):
            if not pair:
                continue
            if "=" not in pair:
                return _json(
                    {"error": "labels must be k=v[,k2=v2...]"},
                    status=400)
            k, v = pair.split("=", 1)
            labels[k] = v
        try:
            window_s = float(req.query.get("window_s", "600"))
            step_raw = req.query.get("step_s")
            step_s = float(step_raw) if step_raw else None
        except ValueError:
            return _json(
                {"error": "window_s and step_s must be numbers"},
                status=400)
        if window_s <= 0 or (step_s is not None and step_s <= 0):
            return _json(
                {"error": "window_s and step_s must be positive"},
                status=400)
        return _json({
            "enabled": True,
            "tick_s": self.history.tick_s,
            "ticks": self.history.ticks,
            "series": self.history.store.query(
                series=series, labels=labels or None,
                window_s=window_s, step_s=step_s),
        })

    async def _profiler_start(self, req: Request) -> Response:
        from kfserving_tpu.tracing import profiler

        try:
            body = json.loads(req.body) if req.body else {}
        except ValueError:
            body = {}
        log_dir = body.get("log_dir", "/tmp/kfs-profile")
        if not profiler.start(log_dir):
            return _json({"error": "profiler already active",
                          "log_dir": profiler.active_dir}, status=409)
        return _json({"profiling": True, "log_dir": log_dir})

    async def _profiler_stop(self, req: Request) -> Response:
        from kfserving_tpu.tracing import profiler

        log_dir = profiler.stop()
        if log_dir is None:
            return _json({"error": "profiler not active"}, status=409)
        return _json({"profiling": False, "log_dir": log_dir})

    # -- lifecycle ---------------------------------------------------------
    def register_model(self, model: Model) -> None:
        if not model.name:
            raise ValueError(
                "Failed to register model, model.name must be provided.")
        self.repository.update(model)
        logger.info("Registering model: %s", model.name)

    async def start_async(self, models: List[Model],
                          host: str = "0.0.0.0") -> None:
        for model in models:
            self.register_model(model)
        for service in self.services:
            await service.start()
        # Residency-managed repositories pin eviction storms into THIS
        # server's flight recorder (thrash evidence beside the request
        # evidence, federated at /debug/flightrecorder).
        residency = getattr(self.repository, "residency", None)
        if residency is not None:
            residency.attach_flight_recorder(
                self.monitoring.flight_recorder)
        # Host KV tiers pin fault-back storms the same way (the device
        # pool churning conversations through the tier faster than
        # they finish is thrash evidence an operator needs pinned).
        for model in self.repository.get_models():
            tier = getattr(getattr(model, "engine", None),
                           "kv_tier", None)
            if tier is not None:
                tier.attach_flight_recorder(
                    self.monitoring.flight_recorder)
        # Device-discipline sanitizer (KFS_SANITIZE=1): violations
        # pin into this server's flight recorder, and the stall
        # watchdog heartbeats the serving loop.  Disabled: two env
        # reads, nothing armed.  Ownership matters: the watchdog is
        # process-global, so only the server that started it stops
        # it — a second in-process server must not tear down the
        # first one's on ITS stop.
        from kfserving_tpu.reliability import sanitizer

        self._owns_sanitizer_watchdog = False
        if sanitizer.enabled():
            self._owns_sanitizer_watchdog = (
                sanitizer.start_watchdog(
                    asyncio.get_running_loop()) is not None)
            if self._owns_sanitizer_watchdog:
                # Only the owning server wires the process-global
                # recorder attachment and armed gauge — a second
                # in-process server must not steal the first one's
                # pinned-violation feed or flip its telemetry.
                sanitizer.attach_flight_recorder(
                    self.monitoring.flight_recorder)
                from kfserving_tpu.observability import metrics as obs

                obs.sanitizer_armed().set(1)
        await self.http_server.start(host, self.http_port)
        self.http_port = self.http_server.port
        if self.grpc_port is not None:
            from kfserving_tpu.server.grpc_server import GRPCServer

            self.grpc_server = GRPCServer(
                self.dataplane, port=self.grpc_port, host=host,
                metrics=self.metrics, monitoring=self.monitoring)
            await self.grpc_server.start()
            self.grpc_port = self.grpc_server.port
        from kfserving_tpu import startup

        # kfslint: disable=async-blocking — mark()'s /proc read is
        # RAM-backed and runs once per process (birth time cached).
        startup.mark("serving")

    async def drain(self, budget_s: float) -> bool:
        """Wait for in-flight work — including live token streams,
        the longest-lived requests in the system — to finish, up to
        `budget_s`.  Returns True when fully drained.  Past the
        budget, stop_async() closes the engines, which delivers a
        terminal error event to every still-open stream (clients see
        a clean end-of-stream, not a dead socket) — the recycle
        contract for generative replicas."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + budget_s
        while loop.time() < deadline:
            busy = (self._admission is not None
                    and self._admission.active > 0)
            if not busy:
                for m in self.repository.get_models():
                    gauges = getattr(getattr(m, "engine", None),
                                     "load_gauges", None)
                    if gauges is None:
                        continue
                    g = gauges()
                    if g["active_slots"] + g["pending"] > 0:
                        busy = True
                        break
            if not busy:
                return True
            await asyncio.sleep(0.1)
        return False

    async def stop_async(self) -> None:
        from kfserving_tpu.reliability import sanitizer

        if getattr(self, "_owns_sanitizer_watchdog", False):
            sanitizer.stop_watchdog()
            # Detach our recorder too: a stopped server's buffer has
            # no /debug surface left, and the global reference would
            # pin this server's object graph for the process life.
            sanitizer.attach_flight_recorder(None)
            self._owns_sanitizer_watchdog = False
            from kfserving_tpu.observability import metrics as obs

            obs.sanitizer_armed().set(0)
        if self.grpc_server is not None:
            await self.grpc_server.stop()
            self.grpc_server = None
        for model in self.repository.get_models():
            close = getattr(model, "close", None)
            if close is not None:
                await close()
        residency = getattr(self.repository, "residency", None)
        if residency is not None:
            residency.close()
        for service in reversed(self.services):
            await service.stop()
        await self.http_server.stop()

    def start(self, models: List[Model]) -> None:
        """Blocking entrypoint, reference kfserver.py:89-108 equivalent."""
        async def _main():
            await self.start_async(models)
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                try:
                    loop.add_signal_handler(sig, stop.set)
                except NotImplementedError:
                    pass
            await stop.wait()
            # SIGTERM drain: let in-flight work (streams included)
            # finish inside the orchestrator's kill grace before the
            # engines close.  Default stays UNDER the orchestrator's
            # TERM_GRACE_S (10 s SIGKILL escalation): past this budget
            # streams get the engines' terminal error event, not the
            # SIGKILL dead socket.
            grace = float(os.environ.get("KFS_DRAIN_GRACE_S", "8"))
            if grace > 0:
                await self.drain(grace)
            # Drain parachute (ISSUE 19): whatever conversation KV is
            # still device-resident — live slots included — exports
            # into the persistent host tier before the engines close,
            # so the successor serves returning users via warm
            # fault-backs instead of full re-prefills.  Bounded by
            # KFS_KV_EXPORT_BUDGET_S; a no-op without a persistent
            # tier dir.
            await self.export_kv()
            await self.stop_async()

        logging.basicConfig(level=logging.INFO)
        asyncio.run(_main())
