"""V2 gRPC server sharing the HTTP server's DataPlane.

The reference mandates the V2 gRPC API (reference
docs/predict-api/v2/grpc_predict_v2.proto:1-328 and required_api.md);
its data plane never implemented it (delegated to Triton).  Here both
protocols front the same `server/dataplane.py` operations — the HTTP
route table and these RPCs are two codecs over one engine path.

grpcio ships no generated service stubs in this image (grpc_tools is
absent), so handlers are registered through
`grpc.method_handlers_generic_handler` against the protoc-generated
message classes — same wire behavior, no _pb2_grpc module needed.

Tensor payloads accept both typed `InferTensorContents` fields and
`raw_input_contents` (required for FP16/BF16); responses mirror the
request's form: raw in -> raw out, typed in -> typed out.
"""

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from kfserving_tpu.protocol.errors import ServingError
from kfserving_tpu.protocol.grpc import pb2
from kfserving_tpu.protocol.v2 import InferInput, InferRequest
from kfserving_tpu.server.dataplane import DataPlane
from kfserving_tpu.tracing import ensure_trace_context

logger = logging.getLogger("kfserving_tpu.grpc")

# datatype -> InferTensorContents field (reference proto comments:
# 8/16/32-bit ints share int_contents / uint_contents).
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents", "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents", "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}

_RAW_DTYPE = {
    "BOOL": np.bool_, "INT8": np.int8, "INT16": np.int16,
    "INT32": np.int32, "INT64": np.int64, "UINT8": np.uint8,
    "UINT16": np.uint16, "UINT32": np.uint32, "UINT64": np.uint64,
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
}


# Shared V2 BYTES framing (protocol/v2.py) — one implementation for
# HTTP binary extension and gRPC raw contents.
from kfserving_tpu.protocol.v2 import (  # noqa: E402
    decode_raw_bytes as _decode_raw_bytes,
    frame_raw_bytes as _encode_raw_bytes,
)


def _tensor_to_numpy(tensor, raw: Optional[bytes]) -> np.ndarray:
    shape = list(tensor.shape)
    if raw is not None:
        if tensor.datatype == "BYTES":
            return np.array(_decode_raw_bytes(raw),
                            dtype=np.object_).reshape(shape)
        if tensor.datatype == "BF16":
            import ml_dtypes

            dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            dtype = np.dtype(_RAW_DTYPE[tensor.datatype])
        return np.frombuffer(raw, dtype=dtype).reshape(shape)
    field = _CONTENTS_FIELD.get(tensor.datatype)
    if field is None:
        raise ValueError(
            f"datatype {tensor.datatype} requires raw_input_contents")
    values = getattr(tensor.contents, field)
    if tensor.datatype == "BYTES":
        return np.array(list(values), dtype=np.object_).reshape(shape)
    return np.asarray(values, dtype=_RAW_DTYPE[tensor.datatype]) \
        .reshape(shape)


def _request_to_infer(req) -> InferRequest:
    raws: List[Optional[bytes]] = list(req.raw_input_contents) or \
        [None] * len(req.inputs)
    if len(raws) != len(req.inputs):
        raise ValueError(
            "raw_input_contents must carry one buffer per input")
    inputs = []
    for tensor, raw in zip(req.inputs, raws):
        arr = _tensor_to_numpy(tensor, raw)
        inputs.append(InferInput(tensor.name, list(tensor.shape),
                                 tensor.datatype, arr))
    return InferRequest(inputs, id=req.id or None)


def _output_to_tensor(out: Dict[str, Any], response, use_raw: bool
                      ) -> None:
    tensor = response.outputs.add()
    tensor.name = out["name"]
    tensor.datatype = out["datatype"]
    tensor.shape.extend(int(s) for s in out["shape"])
    data = out["data"]
    if use_raw:
        if out["datatype"] == "BYTES":
            values = data if isinstance(data, list) else \
                np.asarray(data).ravel().tolist()
            response.raw_output_contents.append(
                _encode_raw_bytes(values))
            return
        if out["datatype"] == "BF16":
            import ml_dtypes

            dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            dtype = _RAW_DTYPE.get(out["datatype"])
        arr = np.asarray(data, dtype=dtype)
        response.raw_output_contents.append(arr.tobytes())
        return
    field = _CONTENTS_FIELD.get(out["datatype"])
    if field is None:  # FP16/BF16 must go raw regardless
        arr = np.asarray(data, dtype=np.float32)
        tensor.datatype = "FP32"
        tensor.ClearField("shape")
        tensor.shape.extend(int(s) for s in out["shape"])
        getattr(tensor.contents, "fp32_contents").extend(
            arr.ravel().tolist())
        return
    values = data if isinstance(data, list) else \
        np.asarray(data).ravel().tolist()
    if out["datatype"] == "BYTES":
        values = [v.encode() if isinstance(v, str) else bytes(v)
                  for v in values]
    getattr(tensor.contents, field).extend(values)


_STATUS_BY_CODE = {404: "NOT_FOUND", 400: "INVALID_ARGUMENT",
                   503: "UNAVAILABLE", 504: "DEADLINE_EXCEEDED"}


def _deadline_from(context):
    """The caller's gRPC deadline as a reliability Deadline, carried
    through the same contextvar channel the HTTP header uses — one
    budget discipline, two wire protocols."""
    from kfserving_tpu.reliability import Deadline

    remaining = context.time_remaining()
    if remaining is None:
        return None
    return Deadline(remaining)


def _http_status(e: Exception) -> int:
    """The HTTP-equivalent status of a handler failure, so gRPC and
    HTTP requests land in the SAME request counter/latency series
    (the recycling watchdog's max_requests trigger scrapes it; a
    gRPC-only deployment must not undercount)."""
    if isinstance(e, ServingError):
        return int(e.status_code)
    if isinstance(e, (ValueError, KeyError)):
        return 400
    return 500


class GRPCServer:
    """Async V2 gRPC front end over a DataPlane."""

    def __init__(self, dataplane: DataPlane, port: int = 0,
                 host: str = "127.0.0.1", metrics=None,
                 monitoring=None):
        self.dataplane = dataplane
        self.port = port
        self.host = host
        self.metrics = metrics  # shared with the HTTP app
        # The HTTP app's Monitoring loop: gRPC requests flight-record
        # (and pin on shed/error) exactly like HTTP ones.  The monitor
        # BUS is not teed here: bus consumers parse JSON V1 payloads,
        # which a proto tensor request doesn't carry.
        self.monitoring = monitoring
        self._server = None

    def _join_trace(self, context) -> Optional[str]:
        """Join the caller's trace from gRPC metadata (`traceparent`
        wins, `x-request-id` fallback) — the gRPC hop's analogue of
        the HTTP header join, so engine spans reached through either
        protocol carry the upstream trace id."""
        try:
            md = {str(k).lower(): str(v) for k, v in
                  (context.invocation_metadata() or ())}
        except Exception:
            md = {}
        return ensure_trace_context(md).trace_id

    def _observe(self, model: str, verb: str, status: int,
                 start: float, trace_id: Optional[str]) -> None:
        latency_ms = (time.perf_counter() - start) * 1000.0
        if self.metrics is not None:
            self.metrics.observe_request(model, verb, status,
                                         latency_ms,
                                         trace_id=trace_id)
        if self.monitoring is not None:
            self.monitoring.record_request(model, verb, status,
                                           latency_ms,
                                           trace_id=trace_id)

    # -- handlers -----------------------------------------------------------
    async def _abort(self, context, e: Exception):
        import grpc

        if isinstance(e, ServingError):
            name = _STATUS_BY_CODE.get(e.status_code, "INTERNAL")
            code = getattr(grpc.StatusCode, name)
            await context.abort(code, e.reason)
        if isinstance(e, (ValueError, KeyError)):
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        logger.exception("grpc handler failed")
        await context.abort(grpc.StatusCode.INTERNAL, str(e))

    async def ServerLive(self, request, context):
        return pb2.ServerLiveResponse(live=self.dataplane.live())

    async def ServerReady(self, request, context):
        return pb2.ServerReadyResponse(ready=self.dataplane.server_ready())

    async def ModelReady(self, request, context):
        try:
            self.dataplane.model_ready(request.name)
            return pb2.ModelReadyResponse(ready=True)
        except ServingError:
            return pb2.ModelReadyResponse(ready=False)

    async def ServerMetadata(self, request, context):
        meta = self.dataplane.server_metadata()
        return pb2.ServerMetadataResponse(
            name=meta["name"], version=meta["version"],
            extensions=meta["extensions"])

    async def ModelMetadata(self, request, context):
        try:
            meta = self.dataplane.model_metadata(request.name)
        except ServingError as e:
            await self._abort(context, e)
        resp = pb2.ModelMetadataResponse(
            name=meta.get("name", request.name),
            platform=meta.get("platform", ""))
        for io_key, target in (("inputs", resp.inputs),
                               ("outputs", resp.outputs)):
            for t in meta.get(io_key, []) or []:
                tm = target.add()
                tm.name = t.get("name", "")
                tm.datatype = t.get("datatype", "")
                tm.shape.extend(int(s) for s in t.get("shape", []))
        return resp

    async def ModelInfer(self, request, context):
        from kfserving_tpu.reliability import deadline_scope

        start = time.perf_counter()
        trace_id = self._join_trace(context)
        try:
            infer_req = _request_to_infer(request)
            with deadline_scope(_deadline_from(context)):
                result = await self.dataplane.infer(
                    request.model_name, infer_req)
        except Exception as e:
            self._observe(request.model_name, "infer",
                          _http_status(e), start, trace_id)
            await self._abort(context, e)
        self._observe(request.model_name, "infer", 200, start,
                      trace_id)
        response = pb2.ModelInferResponse(
            model_name=result.get("model_name", request.model_name),
            model_version=result.get("model_version", ""),
            id=result.get("id", "") or request.id)
        use_raw = bool(request.raw_input_contents)
        for out in result.get("outputs", []):
            _output_to_tensor(out, response, use_raw)
        return response

    # -- generation service (kfs_generate.proto — framework extension,
    # kept separate from the faithful V2 surface) ------------------------
    @staticmethod
    def _generate_body(request) -> Dict[str, Any]:
        """Proto -> the HTTP generate body shape; `optional` fields
        only override the model's config defaults when present."""
        params: Dict[str, Any] = {}
        for field in ("max_tokens", "temperature", "top_k", "top_p",
                      "seed", "logprobs"):
            if request.HasField(field):
                params[field] = getattr(request, field)
        if request.stop:
            params["stop"] = list(request.stop)
        return {"text_input": request.text_input,
                "parameters": params}

    async def Generate(self, request, context):
        from kfserving_tpu.protocol.grpc import kfs_generate_pb2 as gpb
        from kfserving_tpu.reliability import deadline_scope

        start = time.perf_counter()
        trace_id = self._join_trace(context)
        try:
            with deadline_scope(_deadline_from(context)):
                result = await self.dataplane.generate(
                    request.model_name, self._generate_body(request))
        except Exception as e:
            self._observe(request.model_name, "generate",
                          _http_status(e), start, trace_id)
            await self._abort(context, e)
        self._observe(request.model_name, "generate", 200, start,
                      trace_id)
        details = result.get("details", {})
        resp = gpb.GenerateResponse(
            model_name=result.get("model_name", request.model_name),
            text_output=result.get("text_output", ""),
            finish_reason=details.get("finish_reason", ""),
            token_count=details.get("token_count", 0))
        for rec in details.get("logprobs", []) or []:
            resp.chosen_logprobs.add(id=rec["id"],
                                     logprob=rec["logprob"])
            # Full logprob parity with the HTTP generate surface: the
            # top-N alternatives ride a Token per generated token
            # (text stays empty — text_output carries the aggregate).
            tok = resp.tokens.add(id=rec["id"],
                                  logprob=rec["logprob"])
            for top in rec.get("top", []) or []:
                tok.top_logprobs.add(id=top["id"],
                                     logprob=top["logprob"])
        return resp

    async def GenerateStream(self, request, context):
        """Server-streaming tokens over HTTP/2 framing: each yielded
        message is one SSE-event equivalent.  The request validates
        before the first message (gRPC has no committed-headers
        problem, but a clean INVALID_ARGUMENT beats an error mid-
        stream); consumer cancellation propagates to the engine via
        the event stream's close hook."""
        from kfserving_tpu.protocol.grpc import kfs_generate_pb2 as gpb
        from kfserving_tpu.reliability import deadline_scope
        from kfserving_tpu.streams import aclose_quietly

        start = time.perf_counter()
        trace_id = self._join_trace(context)
        try:
            # The deadline covers validation + submission and rides
            # into the engine request: an over-budget stream finishes
            # with reason "timeout" at the next decode-wave boundary.
            with deadline_scope(_deadline_from(context)):
                events = await self.dataplane.generate_stream(
                    request.model_name, self._generate_body(request))
        except Exception as e:
            self._observe(request.model_name, "generate_stream",
                          _http_status(e), start, trace_id)
            await self._abort(context, e)
        status = 200
        try:
            async for event in events:
                msg = gpb.GenerateStreamResponse()
                tok = event.get("token")
                if tok is not None:
                    msg.token.id = (-1 if tok.get("id") is None
                                    else int(tok["id"]))
                    msg.token.text = tok.get("text", "")
                    if "logprob" in tok:
                        msg.token.logprob = float(tok["logprob"])
                    for rec in tok.get("top_logprobs", []):
                        msg.token.top_logprobs.add(
                            id=rec["id"], logprob=rec["logprob"])
                if event.get("finish_reason"):
                    msg.finish_reason = event["finish_reason"]
                    msg.generated_text = event.get(
                        "generated_text", "")
                    msg.token_count = event.get(
                        "details", {}).get("token_count", 0)
                yield msg
        except (GeneratorExit, asyncio.CancelledError):
            # Client cancellation is routine, not a server error:
            # record the nginx-style 499 so disconnect storms never
            # read as a 5xx spike in the request counter.
            status = 499
            raise
        except BaseException:
            status = 500
            raise
        finally:
            # gRPC cancellation (client went away) lands here as a
            # GeneratorExit — close the event stream so the engine
            # frees the decode slot.
            await aclose_quietly(events, "grpc generate stream")
            self._observe(request.model_name, "generate_stream",
                          status, start, trace_id)

    async def RepositoryIndex(self, request, context):
        resp = pb2.RepositoryIndexResponse()
        for entry in self.dataplane.repository_index():
            if request.ready and entry["state"] != "READY":
                continue
            m = resp.models.add()
            m.name = entry["name"]
            m.state = entry["state"]
        return resp

    async def RepositoryModelLoad(self, request, context):
        try:
            await self.dataplane.load(request.model_name)
        except Exception as e:
            await self._abort(context, e)
        return pb2.RepositoryModelLoadResponse()

    async def RepositoryModelUnload(self, request, context):
        try:
            await self.dataplane.unload(request.model_name)
        except Exception as e:
            await self._abort(context, e)
        return pb2.RepositoryModelUnloadResponse()

    # -- lifecycle ----------------------------------------------------------
    def _handlers(self):
        import grpc

        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        inference = grpc.method_handlers_generic_handler(
            "inference.GRPCInferenceService", {
                "ServerLive": unary(self.ServerLive,
                                    pb2.ServerLiveRequest,
                                    pb2.ServerLiveResponse),
                "ServerReady": unary(self.ServerReady,
                                     pb2.ServerReadyRequest,
                                     pb2.ServerReadyResponse),
                "ModelReady": unary(self.ModelReady,
                                    pb2.ModelReadyRequest,
                                    pb2.ModelReadyResponse),
                "ServerMetadata": unary(self.ServerMetadata,
                                        pb2.ServerMetadataRequest,
                                        pb2.ServerMetadataResponse),
                "ModelMetadata": unary(self.ModelMetadata,
                                       pb2.ModelMetadataRequest,
                                       pb2.ModelMetadataResponse),
                "ModelInfer": unary(self.ModelInfer,
                                    pb2.ModelInferRequest,
                                    pb2.ModelInferResponse),
            })
        from kfserving_tpu.protocol.grpc import kfs_generate_pb2 as gpb

        generation = grpc.method_handlers_generic_handler(
            "kfserving.generate.GenerationService", {
                "Generate": unary(self.Generate,
                                  gpb.GenerateRequest,
                                  gpb.GenerateResponse),
                "GenerateStream":
                    grpc.unary_stream_rpc_method_handler(
                        self.GenerateStream,
                        request_deserializer=(
                            gpb.GenerateRequest.FromString),
                        response_serializer=(
                            gpb.GenerateStreamResponse
                            .SerializeToString)),
            })
        repository = grpc.method_handlers_generic_handler(
            "inference.ModelRepositoryService", {
                "RepositoryIndex": unary(
                    self.RepositoryIndex,
                    pb2.RepositoryIndexRequest,
                    pb2.RepositoryIndexResponse),
                "RepositoryModelLoad": unary(
                    self.RepositoryModelLoad,
                    pb2.RepositoryModelLoadRequest,
                    pb2.RepositoryModelLoadResponse),
                "RepositoryModelUnload": unary(
                    self.RepositoryModelUnload,
                    pb2.RepositoryModelUnloadRequest,
                    pb2.RepositoryModelUnloadResponse),
            })
        return [inference, generation, repository]

    async def start(self) -> None:
        import grpc.aio

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(tuple(self._handlers()))
        bound = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        if bound == 0:
            # grpc reports bind failure as port 0, not an exception —
            # indistinguishable from the ephemeral-port request, so
            # surface it loudly instead of "starting" with no listener.
            raise RuntimeError(
                f"gRPC failed to bind {self.host}:{self.port}")
        self.port = bound
        await self._server.start()
        logger.info("V2 gRPC server on %s:%d", self.host, self.port)

    async def stop(self, grace: float = 5.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None
