from kfserving_tpu.server.app import ModelServer

__all__ = ["ModelServer"]
