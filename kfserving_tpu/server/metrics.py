"""Prometheus-text-format metrics for the model server.

The reference delegates request metrics to the Knative queue-proxy
(reference test/benchmark/README.md:5-12) and exposes controller metrics on
:8080 (reference cmd/manager/main.go:60-61).  The TPU server is its own
sidecar-free process, so it exposes request counts/latency histograms and
engine gauges (batch sizes, compile cache, HBM) directly on /metrics.

Built on the labeled registry (observability/registry.py): request
series live on a PRIVATE per-server registry (two servers in one
process must not double-count each other's requests), and `render()`
appends the process-wide REGISTRY so batcher / engine / generator /
reliability series ride the same scrape.  Request latency observations
carry OpenMetrics exemplars linking them to trace ids.
"""

import time
from typing import Dict, Optional

from kfserving_tpu.observability.registry import (
    LATENCY_BUCKETS_MS,
    REGISTRY,
    Registry,
)

# The request counter's series name, shared with every consumer that
# scrapes it (the recycling watchdog's max_requests trigger keys on
# this literal, the SLO engine reads it).  Canonical constants live in
# observability/metrics.py — re-exported here for existing importers.
from kfserving_tpu.observability.metrics import (  # noqa: F401
    REQUEST_LATENCY_SERIES as LATENCY_SERIES,
    REQUEST_TOTAL_SERIES,
)


class Metrics:
    def __init__(self):
        self.registry = Registry()
        self.start_time = time.time()

    def observe_request(self, model: str, verb: str, status: int,
                        latency_ms: float,
                        trace_id: Optional[str] = None) -> None:
        self.registry.counter(
            REQUEST_TOTAL_SERIES,
            "Total requests by model/verb/status").labels(
                model=model, verb=verb, status=str(status)).inc()
        self.registry.histogram(
            LATENCY_SERIES, "Request latency histogram",
            buckets=LATENCY_BUCKETS_MS).labels(
                model=model, verb=verb).observe(latency_ms,
                                                trace_id=trace_id)

    def set_gauge(self, name: str, value: float,
                  labels: Dict[str, str] = None) -> None:
        self.registry.gauge(name).labels(**(labels or {})).set(value)

    def render(self, include_global: bool = True,
               exemplars: bool = False) -> str:
        """``exemplars=True`` only for the OpenMetrics content type —
        the classic text/plain parser rejects exemplar suffixes and
        would drop the entire scrape."""
        lines = self.registry.render_lines(exemplars=exemplars)
        if include_global:
            # Process-wide series (batcher, engine stages, generator
            # TTFT/ITL, breaker/retry/deadline) join the scrape.
            lines += REGISTRY.render_lines(exemplars=exemplars)
        lines.append(
            f"kfserving_tpu_uptime_seconds {time.time() - self.start_time}")
        return "\n".join(lines) + "\n"
