"""Prometheus-text-format metrics for the model server.

The reference delegates request metrics to the Knative queue-proxy
(reference test/benchmark/README.md:5-12) and exposes controller metrics on
:8080 (reference cmd/manager/main.go:60-61).  The TPU server is its own
sidecar-free process, so it exposes request counts/latency histograms and
engine gauges (batch sizes, compile cache, HBM) directly on /metrics.
"""

import bisect
import time
from typing import Dict, List, Tuple

LATENCY_BUCKETS_MS = [0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                      5000, 10000]

# The request counter's series name, shared with every consumer that
# scrapes it (the recycling watchdog's max_requests trigger keys on this
# literal — a rename here without the constant would silently disable
# request-count recycling).
REQUEST_TOTAL_SERIES = "kfserving_tpu_request_total"


class Histogram:
    __slots__ = ("buckets", "counts", "total", "sum")

    def __init__(self, buckets: List[float] = LATENCY_BUCKETS_MS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.total += 1
        self.sum += value


class Metrics:
    def __init__(self):
        self.request_count: Dict[Tuple[str, str, int], int] = {}
        self.latency: Dict[Tuple[str, str], Histogram] = {}
        self.gauges: Dict[str, float] = {}
        self.start_time = time.time()

    def observe_request(self, model: str, verb: str, status: int,
                        latency_ms: float) -> None:
        key = (model, verb, status)
        self.request_count[key] = self.request_count.get(key, 0) + 1
        hkey = (model, verb)
        if hkey not in self.latency:
            self.latency[hkey] = Histogram()
        self.latency[hkey].observe(latency_ms)

    def set_gauge(self, name: str, value: float,
                  labels: Dict[str, str] = None) -> None:
        if labels:
            label_str = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items()))
            self.gauges[f"{name}{{{label_str}}}"] = value
        else:
            self.gauges[name] = value

    def render(self) -> str:
        lines = [
            f"# HELP {REQUEST_TOTAL_SERIES} Total requests by "
            f"model/verb/status",
            f"# TYPE {REQUEST_TOTAL_SERIES} counter",
        ]
        for (model, verb, status), count in sorted(self.request_count.items()):
            lines.append(
                f'{REQUEST_TOTAL_SERIES}{{model="{model}",verb="{verb}",'
                f'status="{status}"}} {count}')
        lines += [
            "# HELP kfserving_tpu_request_latency_ms Request latency histogram",
            "# TYPE kfserving_tpu_request_latency_ms histogram",
        ]
        for (model, verb), hist in sorted(self.latency.items()):
            cumulative = 0
            for bound, count in zip(hist.buckets, hist.counts):
                cumulative += count
                lines.append(
                    f'kfserving_tpu_request_latency_ms_bucket{{model="{model}",'
                    f'verb="{verb}",le="{bound}"}} {cumulative}')
            lines.append(
                f'kfserving_tpu_request_latency_ms_bucket{{model="{model}",'
                f'verb="{verb}",le="+Inf"}} {hist.total}')
            lines.append(
                f'kfserving_tpu_request_latency_ms_sum{{model="{model}",'
                f'verb="{verb}"}} {hist.sum}')
            lines.append(
                f'kfserving_tpu_request_latency_ms_count{{model="{model}",'
                f'verb="{verb}"}} {hist.total}')
        typed = set()
        for name, value in sorted(self.gauges.items()):
            base = name.split("{", 1)[0]
            if base not in typed:
                lines.append(f"# TYPE {base} gauge")
                typed.add(base)
            lines.append(f"{name} {value}")
        lines.append(
            f"kfserving_tpu_uptime_seconds {time.time() - self.start_time}")
        return "\n".join(lines) + "\n"
