"""A small, fast asyncio HTTP/1.1 server.

The reference serves with Tornado and forks worker processes
(reference python/kfserving/kfserving/kfserver.py:89-108).  On TPU a single
process owns the chip, so instead of forking we run one asyncio event loop
and rely on (a) a zero-dependency protocol-level HTTP implementation to keep
per-request overhead low and (b) the dispatch path releasing the loop while
XLA executes.  Supports keep-alive, Content-Length bodies, and chunked
transfer decoding.
"""

import asyncio
import logging
import re
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, unquote

logger = logging.getLogger("kfserving_tpu.http")

MAX_HEADER_BYTES = 64 * 1024
# Same default cap as the reference server's tornado max_buffer_size
# (reference kfserver.py:31).
MAX_BODY_BYTES = 104857600

STATUS_PHRASES = {
    200: "OK", 204: "No Content", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 411: "Length Required",
    413: "Payload Too Large", 500: "Internal Server Error",
    502: "Bad Gateway", 503: "Service Unavailable",
    504: "Gateway Timeout",
}


class Request:
    __slots__ = ("method", "path", "query", "headers", "body", "path_params")

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.path_params: Dict[str, str] = {}


class Response:
    __slots__ = ("status", "body", "headers")

    def __init__(self, body: bytes = b"", status: int = 200,
                 headers: Optional[Dict[str, str]] = None,
                 content_type: str = "application/json"):
        self.status = status
        self.body = body
        self.headers = headers or {}
        self.headers.setdefault("content-type", content_type)


class StreamingResponse(Response):
    """Chunked-transfer response whose body is an async iterator of
    byte chunks — the token-streaming surface for generative models
    (the reference's tornado server has no streaming route at all).
    Each yielded chunk is flushed as one HTTP/1.1 chunk, so clients
    see tokens as they are produced, not at request end."""

    __slots__ = ("chunks",)

    def __init__(self, chunks, status: int = 200,
                 headers: Optional[Dict[str, str]] = None,
                 content_type: str = "text/event-stream"):
        super().__init__(b"", status=status, headers=headers,
                         content_type=content_type)
        self.chunks = chunks


Handler = Callable[[Request], Awaitable[Response]]


class Router:
    """Regex route table; literal-prefix fast path for hot routes."""

    def __init__(self):
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []
        self._exact: Dict[Tuple[str, str], Handler] = {}

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        """`pattern` uses {name} placeholders, e.g. /v1/models/{name}:predict.

        Placeholders match the reference's model-name charset
        (reference kfserver.py:68: `[a-zA-Z0-9_-]+`, we additionally allow
        dots for versioned names).
        """
        if "{" not in pattern:
            self._exact[(method, pattern)] = handler
            return
        parts = re.split(r"\{(\w+)\}", pattern)
        regex = ""
        for i, part in enumerate(parts):
            if i % 2 == 0:
                regex += re.escape(part)
            else:
                regex += f"(?P<{part}>[a-zA-Z0-9_.-]+)"
        self._routes.append((method, re.compile(f"^{regex}$"), handler))

    def resolve(self, method: str, path: str
                ) -> Tuple[Optional[Handler], Dict[str, str]]:
        handler = self._exact.get((method, path))
        if handler is not None:
            return handler, {}
        for m, rx, h in self._routes:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                return h, match.groupdict()
        return None, {}


class _HttpProtocol(asyncio.Protocol):
    __slots__ = ("server", "transport", "_buf", "_expect_body", "_headers",
                 "_method", "_target", "_keepalive", "_chunked", "_task",
                 "_chunk_out", "_chunk_pos", "_can_write")

    def __init__(self, server: "HTTPServer"):
        self.server = server
        self.transport: Optional[asyncio.Transport] = None
        self._buf = bytearray()
        self._expect_body = -1  # -1: parsing headers
        self._headers: Dict[str, str] = {}
        self._method = ""
        self._target = ""
        self._keepalive = True
        self._chunked = False
        self._task: Optional[asyncio.Task] = None
        # Incremental chunked-decoding state (persists across packets so a
        # large chunked body is decoded in O(n), not re-parsed per packet).
        self._chunk_out = bytearray()
        self._chunk_pos = 0
        # Transport write-buffer backpressure (streaming responses wait
        # on this between chunks).
        self._can_write = asyncio.Event()
        self._can_write.set()

    def pause_writing(self):
        self._can_write.clear()

    def resume_writing(self):
        self._can_write.set()

    def connection_made(self, transport):
        self.transport = transport
        try:
            transport.get_extra_info("socket").setsockopt(
                __import__("socket").IPPROTO_TCP,
                __import__("socket").TCP_NODELAY, 1)
        except (OSError, AttributeError):
            pass

    def data_received(self, data: bytes):
        self._buf += data
        self._process()

    def _process(self):
        while True:
            if self._expect_body < 0:
                end = self._buf.find(b"\r\n\r\n")
                if end < 0:
                    if len(self._buf) > MAX_HEADER_BYTES:
                        self._fail(400, "headers too large")
                    return
                head = bytes(self._buf[:end])
                del self._buf[:end + 4]
                try:
                    self._parse_head(head)
                except ValueError as e:
                    self._fail(400, str(e))
                    return
            if self._chunked:
                if len(self._chunk_out) > MAX_BODY_BYTES:
                    self._fail(413, "body too large")
                    return
                body = self._try_dechunk()
                if body is None:
                    return
                self._dispatch(body)
            else:
                if self._expect_body > MAX_BODY_BYTES:
                    self._fail(413, "body too large")
                    return
                if len(self._buf) < self._expect_body:
                    return
                body = bytes(self._buf[:self._expect_body])
                del self._buf[:self._expect_body]
                self._dispatch(body)
            if not self._buf:
                return

    def _parse_head(self, head: bytes):
        lines = head.split(b"\r\n")
        try:
            method, target, _version = lines[0].decode("latin1").split(" ", 2)
        except ValueError:
            raise ValueError("malformed request line")
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            k, _, v = line.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        self._method = method
        self._target = target
        self._headers = headers
        self._keepalive = headers.get("connection", "").lower() != "close"
        te = headers.get("transfer-encoding", "").lower()
        self._chunked = "chunked" in te
        if self._chunked:
            self._expect_body = 0
            self._chunk_out = bytearray()
            self._chunk_pos = 0
        else:
            try:
                self._expect_body = int(headers.get("content-length", "0") or "0")
            except ValueError:
                raise ValueError("invalid content-length")
            if self._expect_body < 0:
                raise ValueError("invalid content-length")

    def _try_dechunk(self) -> Optional[bytes]:
        """Incrementally decode chunked body bytes from the buffer.

        Consumes complete chunks into self._chunk_out as they arrive (O(n)
        over the body); returns the full body when the terminal chunk is
        seen, else None.
        """
        buf = self._buf
        while True:
            nl = buf.find(b"\r\n", self._chunk_pos)
            if nl < 0:
                return None
            try:
                size = int(bytes(buf[self._chunk_pos:nl]).split(b";")[0], 16)
            except ValueError:
                self._fail(400, "bad chunk size")
                return None
            start = nl + 2
            if size == 0:
                tail = buf.find(b"\r\n", start)
                if tail < 0:
                    return None
                del buf[:tail + 2]
                self._chunk_pos = 0
                body = bytes(self._chunk_out)
                self._chunk_out = bytearray()
                return body
            if len(buf) < start + size + 2:
                return None
            self._chunk_out += buf[start:start + size]
            if len(self._chunk_out) > MAX_BODY_BYTES:
                self._fail(413, "body too large")
                return None
            # Drop consumed bytes so the buffer never re-parses old chunks.
            del buf[:start + size + 2]
            self._chunk_pos = 0

    def _dispatch(self, body: bytes):
        method, target, headers = self._method, self._target, self._headers
        keepalive = self._keepalive
        self._expect_body = -1
        self._headers = {}
        path, _, qs = target.partition("?")
        query = dict(parse_qsl(qs)) if qs else {}
        request = Request(method, unquote(path), query, headers, body)
        prev = self._task
        self._task = asyncio.ensure_future(
            self._respond(request, keepalive, prev))

    async def _respond(self, request: Request, keepalive: bool,
                       prev: Optional[asyncio.Task]):
        try:
            response = await self.server.handle(request)
        except Exception:
            logger.exception("unhandled error serving %s %s",
                             request.method, request.path)
            response = Response(b'{"error": "internal server error"}',
                                status=500)
        # Handlers may run concurrently, but responses on one connection
        # must be written in request order (HTTP/1.1 pipelining).
        if prev is not None and not prev.done():
            await asyncio.shield(prev)
        if self.transport is None or self.transport.is_closing():
            # Client gone before the response started.  A streaming
            # body still holds resources (admission slot, engine
            # work) released by its close path — aclose() it here or
            # they leak until GC (and the admission slot leaks
            # forever if the producer wrapper only cleans up on
            # close/exhaustion).
            if isinstance(response, StreamingResponse):
                from kfserving_tpu.streams import aclose_quietly

                await aclose_quietly(response.chunks,
                                     "unstarted stream producer")
            return
        if isinstance(response, StreamingResponse):
            await self._write_streaming(response, keepalive)
            return
        self.transport.write(encode_response(response, keepalive))
        if not keepalive:
            self.transport.close()

    async def _write_streaming(self, response: "StreamingResponse",
                               keepalive: bool):
        phrase = STATUS_PHRASES.get(response.status, "Unknown")
        lines = [f"HTTP/1.1 {response.status} {phrase}"]
        for k, v in response.headers.items():
            lines.append(f"{k}: {v}")
        lines.append("transfer-encoding: chunked")
        lines.append("connection: " + ("keep-alive" if keepalive
                                       else "close"))
        self.transport.write(
            ("\r\n".join(lines) + "\r\n\r\n").encode("latin1"))
        try:
            try:
                async for chunk in response.chunks:
                    if not chunk:
                        continue
                    if self.transport is None or \
                            self.transport.is_closing():
                        return  # client went away: stop producing
                    self.transport.write(b"%x\r\n" % len(chunk) + chunk
                                         + b"\r\n")
                    # Real backpressure: when the transport's write
                    # buffer passes the high-water mark, asyncio calls
                    # pause_writing — wait for resume so a slow client
                    # doesn't buffer the whole generation in memory.
                    await self._can_write.wait()
            except Exception:
                logger.exception("streaming body failed mid-response")
                # Mid-stream failure: the chunked framing is already
                # committed; terminate the connection so the client
                # sees a truncated stream, not a silent success.
                if self.transport is not None:
                    self.transport.close()
                return
            if self.transport is not None and \
                    not self.transport.is_closing():
                self.transport.write(b"0\r\n\r\n")
                if not keepalive:
                    self.transport.close()
        finally:
            # Close the producer NOW on any exit path (client gone,
            # mid-stream failure): its close path releases admission
            # slots and engine work — waiting for GC would leak them.
            from kfserving_tpu.streams import aclose_quietly

            await aclose_quietly(response.chunks, "stream producer")

    def _fail(self, status: int, reason: str):
        # Chain behind any in-flight response so a pipelined connection never
        # sees the failure attributed to an earlier request.
        resp = Response(('{"error": "%s"}' % reason).encode(), status=status)
        prev = self._task
        self._task = asyncio.ensure_future(self._write_failure(resp, prev))

    async def _write_failure(self, resp: Response,
                             prev: Optional[asyncio.Task]):
        if prev is not None and not prev.done():
            await asyncio.shield(prev)
        if self.transport and not self.transport.is_closing():
            self.transport.write(encode_response(resp, False))
            self.transport.close()

    def connection_lost(self, exc):
        self.transport = None
        # Unblock any streaming writer waiting on backpressure; it
        # checks transport is None and stops.
        self._can_write.set()


def encode_response(resp: Response, keepalive: bool) -> bytes:
    phrase = STATUS_PHRASES.get(resp.status, "Unknown")
    lines = [f"HTTP/1.1 {resp.status} {phrase}"]
    for k, v in resp.headers.items():
        lines.append(f"{k}: {v}")
    lines.append(f"content-length: {len(resp.body)}")
    lines.append("connection: " + ("keep-alive" if keepalive else "close"))
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin1")
    return head + resp.body


class HTTPServer:
    def __init__(self, router: Router,
                 error_hook: Optional[Callable[[Request, Exception], Any]] = None):
        self.router = router
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self.error_hook = error_hook

    async def handle(self, request: Request) -> Response:
        handler, params = self.router.resolve(request.method, request.path)
        if handler is None:
            return Response(b'{"error": "not found"}', status=404)
        request.path_params = params
        return await handler(request)

    async def start(self, host: str = "0.0.0.0", port: int = 8080):
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            lambda: _HttpProtocol(self), host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("Listening on port %s", self.port)

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
