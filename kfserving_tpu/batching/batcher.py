"""In-process dynamic request batcher, shape-bucket aware.

Re-implements the observable semantics of the reference Go agent batcher
(reference pkg/batcher/handler.go):

- requests accumulate until `max_batch_size` instances are queued or the
  oldest request has waited `max_latency_ms` (reference handler.go:176-183,
  defaults 32 / 5000ms at handler.go:32-36);
- each caller gets back exactly its own predictions, scattered by index
  (reference handler.go:138-150);
- a batch result whose prediction count mismatches the instance count is an
  error: "size of prediction is not equal to the size of instances"
  (reference handler.go:129-137);
- every flushed batch is tagged with a fresh batch id (reference
  handler.go:107).

Differences, by design (SURVEY.md §7.3):

- **In-process asyncio**, not an HTTP-hairpin sidecar.  The reference POSTs
  the merged batch back through `httptest.NewRecorder` into the next handler
  (handler.go:98-105) — a serialization round-trip per batch.  Here the
  batcher awaits the model's batch callable directly.
- **Event-driven flush.**  The reference polls every 100µs
  (handler.go:33,171); we schedule a per-batch deadline timer and flush
  immediately on size, so flush latency is not quantized.
- **Shape bucketing.**  A `key_fn` partitions requests into independent
  batches (e.g. by padded sequence-length bucket) so one XLA-compiled shape
  serves each batch — the TPU-native concern the reference never had.
- **Engine-aware flushing** (`max_inflight`).  Device execution has a high
  fixed cost per call (runtime round trips dominate small batches), so
  flushing a 3-instance batch every few ms while the engine is busy only
  queues tiny executions.  With `max_inflight=N`, at most N batches are in
  flight; further flush triggers leave the batch accumulating (up to chunk
  limits) and it flushes the moment a slot frees.  Deadline semantics are
  preserved: a request never waits past max_latency once a slot is free,
  and under light load (slots free) the timer flush fires exactly as
  before.
- **Bucket-aligned flushing** (`buckets`).  The engine pads every batch up
  to a compiled bucket size; a 28-instance flush against buckets
  [16, 64, 128] executes 64 slots and discards 36 (56% of the device
  FLOPs).  When the engine's bucket ladder is passed in, a flush takes a
  *prefix* of the pending queue (split at request boundaries) whose size
  is the largest bucket <= pending count, so under sustained load every
  execution is exactly bucket-sized and pad waste comes only from
  drain-out tails.  The un-flushed remainder keeps accumulating under its
  own deadline timer (recomputed from its oldest request's arrival), so
  per-request deadline semantics are unchanged: every request still
  flushes by its own arrival + max_latency (modulo inflight deferral,
  exactly as before).
"""

import asyncio
import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, Hashable, List, Optional

from kfserving_tpu.observability import metrics as obs
from kfserving_tpu.reliability.deadline import (
    Deadline,
    DeadlineExceeded,
    clear_deadline,
    current_deadline,
)
from kfserving_tpu.tracing import Span, current_request_id, tracer

logger = logging.getLogger("kfserving_tpu.batcher")

DEFAULT_MAX_BATCH_SIZE = 32   # reference handler.go:34
DEFAULT_MAX_LATENCY_MS = 5000  # reference handler.go:35


class BatchSizeMismatch(Exception):
    def __init__(self, message: str = "size of prediction is not equal to "
                 "the size of instances"):
        super().__init__(message)


@dataclass
class BatchResult:
    predictions: List[Any]
    batch_id: str


@dataclass
class _Waiter:
    start: int                  # offset of this request's instances
    count: int
    future: asyncio.Future
    # loop.time()-based flush deadline (arrival + max_latency) so a
    # remainder left behind by a prefix flush can re-arm its timer at
    # its own oldest request's deadline.
    flush_at: float = 0.0
    # The request's reliability budget (x-request-timeout-ms / gRPC
    # deadline), captured from the ambient context at submit: a waiter
    # whose budget expires while queued fails with 504 *before* it
    # wastes a batch slot.
    budget: Optional[Deadline] = None
    expiry: Optional[asyncio.TimerHandle] = None
    # The submitting request's trace id, captured at submit like the
    # budget: the flush records a `batcher.queue` span against it so
    # the flight recorder's timeline shows time spent coalescing.
    trace_id: Optional[str] = None


@dataclass
class _Pending:
    instances: List[Any] = field(default_factory=list)
    waiters: List[_Waiter] = field(default_factory=list)
    timer: Optional[asyncio.TimerHandle] = None
    ripe: bool = False  # flush requested but deferred (no inflight slot)


BatchHandler = Callable[[List[Any]], Awaitable[List[Any]]]


class DynamicBatcher:
    """Coalesce per-request instance lists into batched handler calls.

    handler: async callable mapping a list of instances to a same-length list
    of predictions (the whole batch in one call — on the TPU path this is a
    single padded jit invocation).
    key_fn: optional shape-bucket key; requests with different keys never
    share a batch.  The handler receives (instances, key) when key_fn is set.
    """

    def __init__(self, handler: BatchHandler,
                 max_batch_size: int = DEFAULT_MAX_BATCH_SIZE,
                 max_latency_ms: float = DEFAULT_MAX_LATENCY_MS,
                 key_fn: Optional[Callable[[Any], Hashable]] = None,
                 max_inflight: Optional[int] = None,
                 buckets: Optional[List[int]] = None):
        if max_batch_size <= 0:
            max_batch_size = DEFAULT_MAX_BATCH_SIZE
        if max_latency_ms <= 0:
            max_latency_ms = DEFAULT_MAX_LATENCY_MS
        if max_inflight is not None and max_inflight <= 0:
            # 0 would deadlock (every flush defers, nothing ever frees a
            # slot); clamp like the other knobs.
            max_inflight = 1
        self.handler = handler
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.key_fn = key_fn
        self.max_inflight = max_inflight
        # The engine's compiled batch-bucket ladder: flushes split at these
        # boundaries so executed batches pad (near) zero slots.  A chunk
        # must never exceed the largest compiled bucket, so the ladder cap
        # tightens max_batch_size when both are given.
        if buckets:
            from kfserving_tpu.engine.buckets import BucketPolicy

            self._bucket_policy = BucketPolicy(buckets)
            self.buckets = self._bucket_policy.buckets
            self.max_batch_size = min(self.max_batch_size,
                                      self._bucket_policy.max)
        else:
            self._bucket_policy = None
            self.buckets = None
        self._inflight = 0
        self._pending: Dict[Hashable, _Pending] = {}
        # Strong refs to in-flight batch tasks: the event loop holds only
        # weak refs, so an unreferenced task can be GC'd mid-batch.
        self._tasks: set = set()
        # Telemetry for the metrics endpoint / bucket tuning.
        self.batches_flushed = 0
        self.instances_batched = 0
        self.last_batch_size = 0
        # Per-bucket queue age at flush (ms) — the starvation
        # diagnostic: a bucket whose max age >> max_latency_ms is
        # losing slot races (VERDICT r3 weak #3 instrumentation).
        self.queue_age_ms: Dict[Hashable, Dict[str, float]] = {}

    async def submit(self, instances: List[Any]) -> BatchResult:
        """Enqueue one request's instances; resolves with its own predictions."""
        # len() (not truthiness): instances may be a numpy array from the
        # native codec fast path, where bool() on >1 element raises.
        if len(instances) == 0:
            raise ValueError("no instances in the request")
        budget = current_deadline()
        if budget is not None:
            # Already over budget: 504 before touching the queue.
            budget.raise_if_expired("batch queue admission")
        key = self.key_fn(instances[0]) if self.key_fn else None
        loop = asyncio.get_running_loop()
        pending = self._pending.get(key)
        if pending is None:
            pending = _Pending()
            self._pending[key] = pending
            pending.timer = loop.call_later(
                self.max_latency_ms / 1000.0, self._flush_by_timer, key)
        start = len(pending.instances)
        pending.instances.extend(instances)
        future = loop.create_future()
        waiter = _Waiter(start, len(instances), future,
                         loop.time() + self.max_latency_ms / 1000.0,
                         budget, trace_id=current_request_id.get())
        pending.waiters.append(waiter)
        if budget is not None:
            # Fail at the budget's expiry moment, not at the next
            # flush: a 5s flush deadline must not sit on a 50ms
            # budget's 504.
            waiter.expiry = loop.call_later(
                max(0.0, budget.remaining_s()),
                self._expire_waiter, key, waiter)
        if len(pending.instances) >= self.max_batch_size:
            self._begin_flush(key)
        try:
            return await future
        except asyncio.CancelledError:
            # Client disconnect / caller timeout: a cancelled submit
            # withdraws its still-queued instances so they never waste
            # batch-slot capacity (an already-flushed waiter rides its
            # batch out; the result is simply dropped).
            self._discard_waiter(key, waiter)
            if future.done() and not future.cancelled():
                # Retrieve the exception an expiry set in the race
                # window, or asyncio logs "exception was never
                # retrieved" on GC.
                future.exception()
            raise
        finally:
            if waiter.expiry is not None:
                waiter.expiry.cancel()

    def _expire_waiter(self, key: Hashable, waiter: _Waiter) -> None:
        """Budget ran out while queued: fail THIS waiter with 504 and
        withdraw its instances (the rest of the batch is untouched)."""
        if waiter.future.done():
            return
        if not waiter.budget.expired:
            # Timer fired early (clock clamping/drift): the 504 must
            # follow the BUDGET, not timer arithmetic — re-arm.
            waiter.expiry = asyncio.get_running_loop().call_later(
                max(0.001, waiter.budget.remaining_s()),
                self._expire_waiter, key, waiter)
            return
        waiter.future.set_exception(
            DeadlineExceeded("expired in batch queue"))
        self._discard_waiter(key, waiter)

    def _discard_waiter(self, key: Hashable, waiter: _Waiter) -> None:
        """Remove a dead waiter (expired / cancelled) from its pending
        group, rebuilding sibling offsets.  No-op once flushed."""
        pending = self._pending.get(key)
        if pending is None or waiter not in pending.waiters:
            return
        pending.waiters.remove(waiter)
        del pending.instances[waiter.start:waiter.start + waiter.count]
        for w in pending.waiters:
            if w.start > waiter.start:
                w.start -= waiter.count
        if not pending.waiters:
            if pending.timer is not None:
                pending.timer.cancel()
            self._pending.pop(key, None)

    def _reap_dead(self, pending: _Pending) -> None:
        """Drop waiters that can no longer use a result — budget spent
        (fail them with 504 now) or future already done (cancelled) —
        before a flush commits batch slots to them."""
        dead = [w for w in pending.waiters
                if w.future.done()
                or (w.budget is not None and w.budget.expired)]
        if not dead:
            return
        for w in dead:
            if not w.future.done():
                w.future.set_exception(
                    DeadlineExceeded("expired in batch queue"))
            pending.waiters.remove(w)
        instances, pos = [], 0
        for w in pending.waiters:
            instances.extend(
                pending.instances[w.start:w.start + w.count])
            w.start = pos
            pos += w.count
        pending.instances = instances

    def _flush_by_timer(self, key: Hashable):
        if key in self._pending and self._pending[key].instances:
            self._begin_flush(key)

    def _split_prefix(self, pending: _Pending, target: int):
        """Split `pending` at request boundaries into (head, rest) where
        head holds the oldest waiters totalling <= target instances.
        Returns (pending, None) when no split is possible (everything
        fits, or the first waiter alone exceeds target)."""
        cum = j = 0
        for w in pending.waiters:
            if cum + w.count > target:
                break
            cum += w.count
            j += 1
        if j == 0 or j == len(pending.waiters):
            return pending, None
        head = _Pending(instances=pending.instances[:cum],
                        waiters=pending.waiters[:j])
        # ripe is NOT inherited: the remainder's requests are younger —
        # their own deadline timer (re-armed by the caller) or the next
        # size trigger flushes them; marking them ripe would make
        # _on_batch_done flush a tiny padded batch early.
        rest = _Pending(instances=pending.instances[cum:],
                        waiters=pending.waiters[j:])
        for w in rest.waiters:
            w.start -= cum
        return head, rest

    def _begin_flush(self, key: Hashable, align: bool = True):
        pending = self._pending.get(key)
        if pending is None:
            return
        # Shed dead weight first: expired-budget and cancelled waiters
        # must not occupy slots in the batch about to execute.
        self._reap_dead(pending)
        if not pending.waiters:
            if pending.timer is not None:
                pending.timer.cancel()
            self._pending.pop(key, None)
            return
        if self.max_inflight is not None and \
                self._inflight >= self.max_inflight:
            # Engine busy: keep the batch open so more instances coalesce;
            # _on_batch_done flushes it the moment a slot frees.
            pending.ripe = True
            return
        head, rest = pending, None
        if align and self._bucket_policy is not None:
            n = len(pending.instances)
            target = self._bucket_policy.floor_fit(n)
            if target is not None and target < n:
                # Flush exactly a bucket's worth (zero pad slots); the
                # remainder keeps coalescing toward the next boundary.
                head, rest = self._split_prefix(pending, target)
        if pending.timer is not None:
            pending.timer.cancel()
            pending.timer = None
        if rest is not None:
            self._pending[key] = rest
            # Re-arm at the remainder's own oldest deadline (may be in
            # the past if this flush was slot-deferred — fires ~now).
            loop = asyncio.get_running_loop()
            rest.timer = loop.call_at(rest.waiters[0].flush_at,
                                      self._flush_by_timer, key)
        else:
            self._pending.pop(key)
        if head.waiters:
            loop = asyncio.get_running_loop()
            oldest_arrival = head.waiters[0].flush_at \
                - self.max_latency_ms / 1000.0
            age_ms = max(0.0, (loop.time() - oldest_arrival) * 1000.0)
            rec = self.queue_age_ms.setdefault(
                key, {"max": 0.0, "last": 0.0})
            rec["last"] = round(age_ms, 1)
            rec["max"] = round(max(rec["max"], age_ms), 1)
            # Stage-timing series (InferLine's per-stage visibility):
            # every flushed request's queue wait, and the flush's fill
            # ratio against the bucket it will execute in (1.0 = no
            # pad slots burned).
            wait_hist = obs.batch_queue_wait_ms()
            now = loop.time()
            n = len(head.instances)
            if self._bucket_policy is not None:
                padded = self._bucket_policy.fit(
                    min(n, self.max_batch_size)) or n
            else:
                padded = self.max_batch_size
            fill = min(1.0, n / padded)
            for w in head.waiters:
                wait_ms = max(
                    0.0, (now - (w.flush_at
                                 - self.max_latency_ms / 1000.0))
                    * 1000.0)
                wait_hist.labels(bucket=str(key)).observe(wait_ms)
                if w.trace_id is not None:
                    # One completed `batcher.queue` span per flushed
                    # request: the flight recorder's view of time
                    # spent coalescing, and of the batch fill its
                    # wait bought.
                    tracer.record(Span(
                        w.trace_id, "batcher.queue",
                        time.time() - wait_ms / 1000.0, wait_ms,
                        {"bucket": str(key), "batch": n,
                         "fill": round(fill, 4)}))
            obs.batch_fill_ratio().labels(bucket=str(key)).observe(fill)
            # Host-track timeline event: one marker per flushed batch
            # (size, bucket, fill) — the batcher-fill lane of the
            # /debug/profile trace.
            from kfserving_tpu.observability.profiling import TIMELINE

            TIMELINE.record("host", "batch.flush",
                            attrs={"bucket": str(key), "batch": n,
                                   "fill": round(fill, 4)})
        self._inflight += 1
        task = asyncio.ensure_future(self._run_batch(key, head))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        if rest is not None and \
                len(rest.instances) >= self.max_batch_size:
            # A single giant waiter can leave an over-cap remainder; the
            # size trigger lives in submit(), so re-trigger here or it
            # would idle until its deadline.
            self._begin_flush(key, align=align)

    def _on_batch_done(self):
        self._inflight -= 1
        # Flush the deferred batch whose OLDEST request has waited
        # longest (earliest deadline), largest batch as tiebreak.
        # Sorting by size alone starved short seq buckets: with
        # singleton deferrals the tiebreak fell through to the bucket
        # KEY, so the 512 bucket always beat the 32 bucket for a freed
        # slot — the r3 mixed-length inversion (len24 p99 1.9s vs
        # len450 1.3s) was this line.
        ripe = [(p.waiters[0].flush_at, -len(p.instances), id(p), k)
                for k, p in self._pending.items()
                if p.ripe and p.instances]
        if ripe:
            ripe.sort()
            self._begin_flush(ripe[0][3])

    async def _run_batch(self, key: Hashable, pending: _Pending):
        # This task inherits the context of whichever request's submit
        # (or timer) triggered the flush; the batch serves MANY
        # requests, so that single request's deadline must not govern
        # the shared execution (budgets were enforced per-waiter at
        # flush time).
        clear_deadline()
        batch_id = str(uuid.uuid4())
        try:
            predictions = await self._run_chunked(pending.instances, key)
        except Exception as e:
            for w in pending.waiters:
                if not w.future.done():
                    w.future.set_exception(
                        e if len(pending.waiters) == 1 else _clone_exc(e))
            return
        finally:
            self._on_batch_done()
        self.batches_flushed += 1
        self.instances_batched += len(pending.instances)
        self.last_batch_size = len(pending.instances)
        for w in pending.waiters:
            if not w.future.done():
                w.future.set_result(BatchResult(
                    predictions[w.start:w.start + w.count], batch_id))

    async def _run_chunked(self, instances: List[Any],
                           key: Hashable) -> List[Any]:
        """Execute a flush in handler calls of at most ``max_batch_size``.

        Coalescing can overshoot the cap (31 pending + a 20-instance
        arrival = 51), and a single request may exceed it outright; the
        engine's largest compiled bucket is ``max_batch_size``, so the
        handler must never see more (the reference's downstream server
        takes any size, pkg/batcher/handler.go:98-154 — the TPU build
        chunks instead).  Chunks run concurrently so the engine's pipeline
        can overlap them; results re-concatenate in order.
        """
        sizes = self._chunk_sizes(len(instances))
        if len(sizes) == 1:
            chunks = [instances]
        else:
            chunks, pos = [], 0
            for s in sizes:
                chunks.append(instances[pos:pos + s])
                pos += s
        if self.key_fn is not None:
            coros = [self.handler(c, key) for c in chunks]
        else:
            coros = [self.handler(c) for c in chunks]
        # return_exceptions: a failing chunk must not leave sibling chunks
        # running untracked — flush()'s shutdown drain guarantees every
        # handler call has finished before the engine is torn down.
        results = await asyncio.gather(*coros, return_exceptions=True)
        for preds in results:
            if isinstance(preds, BaseException):
                raise preds
        for chunk, preds in zip(chunks, results):
            if len(preds) != len(chunk):
                raise BatchSizeMismatch()
        if len(results) == 1:
            return results[0]
        return [p for preds in results for p in preds]

    def _chunk_sizes(self, n: int) -> List[int]:
        """Split an n-instance flush into handler-call sizes.

        Without a bucket ladder: chunks of max_batch_size (the engine's
        largest compiled shape).  With one: greedy largest-bucket-first,
        then merge the trailing fragment into its neighbor when that
        doesn't increase total padded slots (90 with [16,64,128] ->
        [64, 26] = 96 padded slots, vs 128 for a single call)."""
        cap = self.max_batch_size  # __init__ clamps cap <= max(buckets)
        if self._bucket_policy is None:
            if n <= cap:
                return [n]
            return [cap] * (n // cap) + ([n % cap] if n % cap else [])
        sizes, rem = [], n
        while rem > 0:
            b = self._bucket_policy.floor_fit(min(rem, cap))
            if b is None:
                sizes.append(rem)  # below the smallest bucket: one padded
                break              # call, nothing smaller is compiled
            sizes.append(b)
            rem -= b

        def padded(m: int) -> int:
            return self._bucket_policy.fit(m) or m

        while len(sizes) >= 2:
            merged = sizes[-1] + sizes[-2]
            if merged <= cap and \
                    padded(merged) <= padded(sizes[-1]) + padded(sizes[-2]):
                sizes[-2:] = [merged]  # equal waste, one fewer dispatch
            else:
                break
        return sizes

    async def flush(self):
        """Force-flush all pending batches and drain in-flight ones
        (shutdown path): returns only once every spawned batch task has
        completed and all waiter futures are resolved.  align=False: a
        drain must not leave a remainder behind (and the loop re-checks
        _pending because a slot-deferred flush may have been re-queued by
        _on_batch_done with alignment, leaving a remainder)."""
        while True:
            for key in list(self._pending.keys()):
                self._begin_flush(key, align=False)
            if not self._tasks:
                if any(p.instances for p in self._pending.values()):
                    continue  # deferred while tasks drained; flush again
                break
            await asyncio.gather(*list(self._tasks), return_exceptions=True)


def _clone_exc(e: Exception) -> Exception:
    """Best-effort per-waiter copy of a batch failure; falls back to the
    shared instance (type preservation matters more than isolation — HTTP
    status mapping dispatches on the exception class)."""
    try:
        return type(e)(*e.args)
    except Exception:
        return e
