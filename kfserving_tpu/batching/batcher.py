"""In-process dynamic request batcher, shape-bucket aware.

Re-implements the observable semantics of the reference Go agent batcher
(reference pkg/batcher/handler.go):

- requests accumulate until `max_batch_size` instances are queued or the
  oldest request has waited `max_latency_ms` (reference handler.go:176-183,
  defaults 32 / 5000ms at handler.go:32-36);
- each caller gets back exactly its own predictions, scattered by index
  (reference handler.go:138-150);
- a batch result whose prediction count mismatches the instance count is an
  error: "size of prediction is not equal to the size of instances"
  (reference handler.go:129-137);
- every flushed batch is tagged with a fresh batch id (reference
  handler.go:107).

Differences, by design (SURVEY.md §7.3):

- **In-process asyncio**, not an HTTP-hairpin sidecar.  The reference POSTs
  the merged batch back through `httptest.NewRecorder` into the next handler
  (handler.go:98-105) — a serialization round-trip per batch.  Here the
  batcher awaits the model's batch callable directly.
- **Event-driven flush.**  The reference polls every 100µs
  (handler.go:33,171); we schedule a per-batch deadline timer and flush
  immediately on size, so flush latency is not quantized.
- **Shape bucketing.**  A `key_fn` partitions requests into independent
  batches (e.g. by padded sequence-length bucket) so one XLA-compiled shape
  serves each batch — the TPU-native concern the reference never had.
- **Engine-aware flushing** (`max_inflight`).  Device execution has a high
  fixed cost per call (runtime round trips dominate small batches), so
  flushing a 3-instance batch every few ms while the engine is busy only
  queues tiny executions.  With `max_inflight=N`, at most N batches are in
  flight; further flush triggers leave the batch accumulating (up to chunk
  limits) and it flushes the moment a slot frees.  Deadline semantics are
  preserved: a request never waits past max_latency once a slot is free,
  and under light load (slots free) the timer flush fires exactly as
  before.
"""

import asyncio
import logging
import uuid
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, Hashable, List, Optional

logger = logging.getLogger("kfserving_tpu.batcher")

DEFAULT_MAX_BATCH_SIZE = 32   # reference handler.go:34
DEFAULT_MAX_LATENCY_MS = 5000  # reference handler.go:35


class BatchSizeMismatch(Exception):
    def __init__(self, message: str = "size of prediction is not equal to "
                 "the size of instances"):
        super().__init__(message)


@dataclass
class BatchResult:
    predictions: List[Any]
    batch_id: str


@dataclass
class _Pending:
    instances: List[Any] = field(default_factory=list)
    waiters: List = field(default_factory=list)  # (start, count, future)
    timer: Optional[asyncio.TimerHandle] = None
    ripe: bool = False  # flush requested but deferred (no inflight slot)


BatchHandler = Callable[[List[Any]], Awaitable[List[Any]]]


class DynamicBatcher:
    """Coalesce per-request instance lists into batched handler calls.

    handler: async callable mapping a list of instances to a same-length list
    of predictions (the whole batch in one call — on the TPU path this is a
    single padded jit invocation).
    key_fn: optional shape-bucket key; requests with different keys never
    share a batch.  The handler receives (instances, key) when key_fn is set.
    """

    def __init__(self, handler: BatchHandler,
                 max_batch_size: int = DEFAULT_MAX_BATCH_SIZE,
                 max_latency_ms: float = DEFAULT_MAX_LATENCY_MS,
                 key_fn: Optional[Callable[[Any], Hashable]] = None,
                 max_inflight: Optional[int] = None):
        if max_batch_size <= 0:
            max_batch_size = DEFAULT_MAX_BATCH_SIZE
        if max_latency_ms <= 0:
            max_latency_ms = DEFAULT_MAX_LATENCY_MS
        if max_inflight is not None and max_inflight <= 0:
            # 0 would deadlock (every flush defers, nothing ever frees a
            # slot); clamp like the other knobs.
            max_inflight = 1
        self.handler = handler
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.key_fn = key_fn
        self.max_inflight = max_inflight
        self._inflight = 0
        self._pending: Dict[Hashable, _Pending] = {}
        # Strong refs to in-flight batch tasks: the event loop holds only
        # weak refs, so an unreferenced task can be GC'd mid-batch.
        self._tasks: set = set()
        # Telemetry for the metrics endpoint / bucket tuning.
        self.batches_flushed = 0
        self.instances_batched = 0
        self.last_batch_size = 0

    async def submit(self, instances: List[Any]) -> BatchResult:
        """Enqueue one request's instances; resolves with its own predictions."""
        # len() (not truthiness): instances may be a numpy array from the
        # native codec fast path, where bool() on >1 element raises.
        if len(instances) == 0:
            raise ValueError("no instances in the request")
        key = self.key_fn(instances[0]) if self.key_fn else None
        loop = asyncio.get_running_loop()
        pending = self._pending.get(key)
        if pending is None:
            pending = _Pending()
            self._pending[key] = pending
            pending.timer = loop.call_later(
                self.max_latency_ms / 1000.0, self._flush_by_timer, key)
        start = len(pending.instances)
        pending.instances.extend(instances)
        future = loop.create_future()
        pending.waiters.append((start, len(instances), future))
        if len(pending.instances) >= self.max_batch_size:
            self._begin_flush(key)
        return await future

    def _flush_by_timer(self, key: Hashable):
        if key in self._pending and self._pending[key].instances:
            self._begin_flush(key)

    def _begin_flush(self, key: Hashable):
        pending = self._pending.get(key)
        if pending is None:
            return
        if self.max_inflight is not None and \
                self._inflight >= self.max_inflight:
            # Engine busy: keep the batch open so more instances coalesce;
            # _on_batch_done flushes it the moment a slot frees.
            pending.ripe = True
            return
        self._pending.pop(key)
        if pending.timer is not None:
            pending.timer.cancel()
        self._inflight += 1
        task = asyncio.ensure_future(self._run_batch(key, pending))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _on_batch_done(self):
        self._inflight -= 1
        # Flush the ripest (largest) deferred batch into the freed slot.
        ripe = [(len(p.instances), k) for k, p in self._pending.items()
                if p.ripe and p.instances]
        if ripe:
            ripe.sort(reverse=True)
            self._begin_flush(ripe[0][1])

    async def _run_batch(self, key: Hashable, pending: _Pending):
        batch_id = str(uuid.uuid4())
        try:
            predictions = await self._run_chunked(pending.instances, key)
        except Exception as e:
            for _, _, future in pending.waiters:
                if not future.done():
                    future.set_exception(
                        e if len(pending.waiters) == 1 else _clone_exc(e))
            return
        finally:
            self._on_batch_done()
        self.batches_flushed += 1
        self.instances_batched += len(pending.instances)
        self.last_batch_size = len(pending.instances)
        for start, count, future in pending.waiters:
            if not future.done():
                future.set_result(BatchResult(
                    predictions[start:start + count], batch_id))

    async def _run_chunked(self, instances: List[Any],
                           key: Hashable) -> List[Any]:
        """Execute a flush in handler calls of at most ``max_batch_size``.

        Coalescing can overshoot the cap (31 pending + a 20-instance
        arrival = 51), and a single request may exceed it outright; the
        engine's largest compiled bucket is ``max_batch_size``, so the
        handler must never see more (the reference's downstream server
        takes any size, pkg/batcher/handler.go:98-154 — the TPU build
        chunks instead).  Chunks run concurrently so the engine's pipeline
        can overlap them; results re-concatenate in order.
        """
        n = self.max_batch_size
        if len(instances) <= n:
            chunks = [instances]
        else:
            chunks = [instances[i:i + n] for i in range(0, len(instances), n)]
        if self.key_fn is not None:
            coros = [self.handler(c, key) for c in chunks]
        else:
            coros = [self.handler(c) for c in chunks]
        # return_exceptions: a failing chunk must not leave sibling chunks
        # running untracked — flush()'s shutdown drain guarantees every
        # handler call has finished before the engine is torn down.
        results = await asyncio.gather(*coros, return_exceptions=True)
        for preds in results:
            if isinstance(preds, BaseException):
                raise preds
        for chunk, preds in zip(chunks, results):
            if len(preds) != len(chunk):
                raise BatchSizeMismatch()
        if len(results) == 1:
            return results[0]
        return [p for preds in results for p in preds]

    async def flush(self):
        """Force-flush all pending batches and drain in-flight ones
        (shutdown path): returns only once every spawned batch task has
        completed and all waiter futures are resolved."""
        for key in list(self._pending.keys()):
            self._begin_flush(key)
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)


def _clone_exc(e: Exception) -> Exception:
    """Best-effort per-waiter copy of a batch failure; falls back to the
    shared instance (type preservation matters more than isolation — HTTP
    status mapping dispatches on the exception class)."""
    try:
        return type(e)(*e.args)
    except Exception:
        return e
