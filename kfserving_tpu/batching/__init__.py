from kfserving_tpu.batching.batcher import BatchResult, DynamicBatcher

__all__ = ["DynamicBatcher", "BatchResult"]
