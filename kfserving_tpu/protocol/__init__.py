"""Standardized inference protocols (V1 and V2) and CloudEvents support."""

from kfserving_tpu.protocol.errors import (
    InferenceError,
    InvalidInput,
    ModelNotFound,
    ModelNotReady,
    ServingError,
)

__all__ = [
    "ServingError",
    "InvalidInput",
    "ModelNotFound",
    "ModelNotReady",
    "InferenceError",
]
