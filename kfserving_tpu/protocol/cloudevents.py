"""Minimal CloudEvents v1.0 support (binary + structured HTTP modes).

The reference data plane accepts CloudEvents-wrapped predict payloads and
echoes responses as CloudEvents (reference python/kfserving/kfserving/
handlers/http.py:53-112, kfmodel.py:56-88) using the `cloudevents` SDK; the
payload logger emits request/response events
(reference pkg/logger/worker.go:81-119).  That SDK is not a dependency here;
this module implements the small subset the serving path needs:

- binary mode: attributes ride `ce-*` HTTP headers, data is the raw body;
- structured mode: the body is a JSON envelope with a `data` member
  (content-type application/cloudevents+json).
"""

import json
import time
import uuid
from typing import Any, Dict, Optional, Tuple

REQUIRED_ATTRS = ("id", "source", "specversion", "type")
STRUCTURED_CONTENT_TYPE = "application/cloudevents+json"


class CloudEvent:
    def __init__(self, attributes: Dict[str, str], data: Any):
        self.attributes = dict(attributes)
        self.attributes.setdefault("specversion", "1.0")
        self.attributes.setdefault("id", str(uuid.uuid4()))
        self.data = data

    def __getitem__(self, key: str) -> str:
        return self.attributes[key]


def is_binary(headers: Dict[str, str]) -> bool:
    return "ce-specversion" in headers


def is_structured(headers: Dict[str, str]) -> bool:
    ctype = headers.get("content-type", "")
    return ctype.startswith(STRUCTURED_CONTENT_TYPE)


def has_ce_headers(headers: Dict[str, str]) -> bool:
    """Binary-header sniff matching the SDK's has_binary_headers: the spec's
    required attributes present as ce- headers."""
    return ("ce-specversion" in headers and "ce-source" in headers
            and "ce-type" in headers and "ce-id" in headers)


def from_http(headers: Dict[str, str], body: bytes) -> CloudEvent:
    """Decode either binary or structured mode from an HTTP request."""
    if is_structured(headers):
        envelope = json.loads(body.decode("utf-8"))
        missing = [a for a in REQUIRED_ATTRS if a not in envelope]
        if missing:
            raise ValueError(f"CloudEvent missing required fields: {missing}")
        data = envelope.get("data")
        if data is None and "data_base64" in envelope:
            import base64

            data = base64.b64decode(envelope["data_base64"])
        attrs = {k: v for k, v in envelope.items()
                 if k not in ("data", "data_base64")}
        return CloudEvent(attrs, data)
    # binary mode: the content-type HTTP header carries the datacontenttype
    # attribute (CE spec HTTP binding §3.1); keep a "content-type" alias for
    # reference-SDK attribute parity (test_server.py:146-149 asserts both).
    attrs = {k[3:]: v for k, v in headers.items() if k.startswith("ce-")}
    missing = [a for a in REQUIRED_ATTRS if a not in attrs]
    if missing:
        raise ValueError(f"CloudEvent missing required fields: {missing}")
    ctype = headers.get("content-type")
    if ctype:
        attrs.setdefault("datacontenttype", ctype)
        attrs.setdefault("content-type", ctype)
    return CloudEvent(attrs, body)


def _np_default(obj):
    """numpy arrays (native-codec fast path responses) serialize as lists."""
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return tolist()
    raise TypeError(
        f"Object of type {type(obj).__name__} is not JSON serializable")


def ce_time_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())


def to_binary(event: CloudEvent) -> Tuple[Dict[str, str], bytes]:
    headers = {f"ce-{k}": str(v) for k, v in event.attributes.items()
               if k != "content-type"}
    headers["ce-time"] = ce_time_now()
    # datacontenttype rides the plain content-type header too (CE HTTP
    # binding; reference response asserts both, test_server.py:257-263).
    dct = event.attributes.get("datacontenttype")
    if dct:
        headers["content-type"] = dct
    data = event.data
    if isinstance(data, bytes):
        body = data
    else:
        body = json.dumps(data, default=_np_default).encode("utf-8")
        headers.setdefault("content-type", "application/json")
    return headers, body


def to_structured(event: CloudEvent) -> Tuple[Dict[str, str], bytes]:
    envelope = dict(event.attributes)
    envelope["time"] = ce_time_now()
    data = event.data
    if isinstance(data, bytes):
        try:
            envelope["data"] = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            import base64

            envelope["data_base64"] = base64.b64encode(data).decode("ascii")
    else:
        envelope["data"] = data
    return ({"content-type": STRUCTURED_CONTENT_TYPE},
            json.dumps(envelope, default=_np_default).encode("utf-8"))


def new_event(event_type: str, source: str, data: Any,
              extensions: Optional[Dict[str, str]] = None) -> CloudEvent:
    attrs = {"type": event_type, "source": source, "specversion": "1.0",
             "id": str(uuid.uuid4())}
    if extensions:
        attrs.update(extensions)
    return CloudEvent(attrs, data)
