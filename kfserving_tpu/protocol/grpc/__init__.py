"""Generated V2 gRPC protocol messages (see protos/grpc_predict_v2.proto).

`grpc_predict_v2_pb2` is produced by protoc; regenerate with:
    protoc --python_out=kfserving_tpu/protocol/grpc \
        --proto_path=protos grpc_predict_v2.proto
"""

from kfserving_tpu.protocol.grpc import grpc_predict_v2_pb2 as pb2

__all__ = ["pb2"]
