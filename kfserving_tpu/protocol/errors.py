"""Serving error taxonomy mapped to HTTP status codes.

Mirrors the status codes raised by the reference data plane
(reference python/kfserving/kfserving/handlers/http.py and kfserver.py):
400 for malformed input, 404 for unknown model, 503 for not-ready,
500 for inference failure.
"""

from http import HTTPStatus


class ServingError(Exception):
    """Base class; carries an HTTP status code and a reason string."""

    status_code: int = HTTPStatus.INTERNAL_SERVER_ERROR

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class InvalidInput(ServingError):
    """Malformed request payload (reference handlers/http.py:43-51)."""

    status_code = HTTPStatus.BAD_REQUEST


class ModelNotFound(ServingError):
    """Unknown model name (reference kfserver.py:125-129)."""

    status_code = HTTPStatus.NOT_FOUND

    def __init__(self, name: str):
        super().__init__(f"Model with name {name} does not exist.")
        self.name = name


class ModelNotReady(ServingError):
    """Model exists but is not loaded/ready (reference kfserver.py:131-135)."""

    status_code = HTTPStatus.SERVICE_UNAVAILABLE

    def __init__(self, name: str, detail: str = ""):
        reason = f"Model with name {name} is not ready."
        if detail:
            reason = f"{reason} {detail}"
        super().__init__(reason)
        self.name = name


class InferenceError(ServingError):
    """Model execution failed."""

    status_code = HTTPStatus.INTERNAL_SERVER_ERROR
