"""V1 predict protocol: `{"instances": [...]}` -> `{"predictions": [...]}`.

The request schema is the TF-Serving style row format used by the reference
(reference python/kfserving/kfserving/handlers/http.py:43-51 validates that
"instances"/"inputs" is a list; per-framework servers consume
`request["instances"]`, e.g. reference python/sklearnserver/sklearnserver/
model.py:42-53).
"""

from typing import Any, Dict, List

from kfserving_tpu.protocol.errors import InvalidInput


def validate_request(request: Dict[str, Any]) -> Dict[str, Any]:
    """Validate a decoded V1 request body.

    Matches the reference check (handlers/http.py:43-51): if "instances" or
    "inputs" is present it must be a list.  Unlike the reference we also
    reject non-dict bodies early with a clear message.
    """
    if not isinstance(request, dict):
        raise InvalidInput('Expected request body to be a JSON object')
    for key in ("instances", "inputs"):
        value = request.get(key)
        if value is None:
            continue
        # Accepted: JSON lists, or numpy arrays from the native codec fast
        # path (protocol/native.py).
        if not (isinstance(value, list) or hasattr(value, "ndim")):
            raise InvalidInput(
                'Expected "instances" or "inputs" to be a list')
    return request


def get_instances(request: Dict[str, Any]) -> List[Any]:
    """Extract the instance list from a V1 request ("instances" or "inputs")."""
    validate_request(request)
    if "instances" in request:
        return request["instances"]
    if "inputs" in request:
        return request["inputs"]
    raise InvalidInput('Expected "instances" or "inputs" in request body')


def make_response(predictions: List[Any]) -> Dict[str, Any]:
    return {"predictions": predictions}
