"""Minimal Avro binary codec (schema-driven decode + encode).

The reference data plane accepts avro-encoded CloudEvents payloads: the
server hands the raw bytes through to the model, which decodes them with
the `avro` library (reference python/kfserving/test/test_server.py:143-314,
DummyAvroCEModel._parserequest).  That library is not a dependency of this
framework; this module implements the subset of the Avro 1.x binary
encoding needed to read and write datum payloads against a JSON schema:

- primitives: null, boolean, int, long (zigzag varint), float, double,
  bytes, string
- complex: record, enum, array, map, union, fixed

No object-container files (no sync markers / block compression) — the
CloudEvents path carries bare datum bytes, which is all the reference
exercises.  Schemas are plain parsed-JSON values (dict / list / str),
matching `avro.schema.parse(...)` input.
"""

import io
import json
import struct
from typing import Any, Dict, List, Union

Schema = Union[str, Dict[str, Any], List[Any]]

PRIMITIVES = {"null", "boolean", "int", "long", "float", "double",
              "bytes", "string"}


def parse_schema(source: Union[str, bytes, Schema]) -> Schema:
    """Accept a JSON string (like avro.schema.parse) or pre-parsed JSON.
    A bare primitive name ("long") is valid shorthand for its schema."""
    if isinstance(source, bytes):
        source = source.decode("utf-8")
    if isinstance(source, str):
        stripped = source.strip()
        if stripped in PRIMITIVES:
            return stripped
        return json.loads(stripped)
    return source


def _named_types(schema: Schema, registry: Dict[str, Schema]) -> None:
    """Index named types (record/enum/fixed) so schemas can self-reference."""
    if isinstance(schema, dict):
        t = schema.get("type")
        name = schema.get("name")
        if name and t in ("record", "enum", "fixed"):
            ns = schema.get("namespace")
            registry[name] = schema
            if ns:
                registry[f"{ns}.{name}"] = schema
        if t == "record":
            for f in schema.get("fields", []):
                _named_types(f.get("type"), registry)
        elif t == "array":
            _named_types(schema.get("items"), registry)
        elif t == "map":
            _named_types(schema.get("values"), registry)
    elif isinstance(schema, list):
        for branch in schema:
            _named_types(branch, registry)


class _Reader:
    def __init__(self, buf: bytes):
        self._io = io.BytesIO(buf)

    def read(self, n: int) -> bytes:
        out = self._io.read(n)
        if len(out) != n:
            raise ValueError("truncated avro payload")
        return out

    def read_long(self) -> int:
        """Zigzag-encoded variable-length integer (int and long alike)."""
        shift, accum = 0, 0
        while True:
            b = self.read(1)[0]
            accum |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 70:
                raise ValueError("varint too long for avro long")
        return (accum >> 1) ^ -(accum & 1)

    def read_bytes(self) -> bytes:
        n = self.read_long()
        if n < 0:
            raise ValueError("negative avro bytes length")
        return self.read(n)


class _Writer:
    def __init__(self):
        self._io = io.BytesIO()

    def write(self, b: bytes) -> None:
        self._io.write(b)

    def write_long(self, value: int) -> None:
        datum = (value << 1) ^ (value >> 63)
        while True:
            chunk = datum & 0x7F
            datum >>= 7
            if datum:
                self._io.write(bytes([chunk | 0x80]))
            else:
                self._io.write(bytes([chunk]))
                break

    def write_bytes(self, value: bytes) -> None:
        self.write_long(len(value))
        self._io.write(value)

    def getvalue(self) -> bytes:
        return self._io.getvalue()


def _schema_type(schema: Schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def _read_datum(r: _Reader, schema: Schema,
                registry: Dict[str, Schema]) -> Any:
    if isinstance(schema, str) and schema not in PRIMITIVES:
        schema = registry[schema]  # named-type reference
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return r.read(1) != b"\x00"
    if t in ("int", "long"):
        return r.read_long()
    if t == "float":
        return struct.unpack("<f", r.read(4))[0]
    if t == "double":
        return struct.unpack("<d", r.read(8))[0]
    if t == "bytes":
        return r.read_bytes()
    if t == "string":
        return r.read_bytes().decode("utf-8")
    if t == "union":
        branches = schema if isinstance(schema, list) else schema["type"]
        idx = r.read_long()
        if not 0 <= idx < len(branches):
            raise ValueError(f"avro union index {idx} out of range")
        return _read_datum(r, branches[idx], registry)
    if t == "record":
        return {f["name"]: _read_datum(r, f["type"], registry)
                for f in schema["fields"]}
    if t == "enum":
        idx = r.read_long()
        symbols = schema["symbols"]
        if not 0 <= idx < len(symbols):
            raise ValueError(f"avro enum index {idx} out of range")
        return symbols[idx]
    if t == "fixed":
        return r.read(schema["size"])
    if t == "array":
        out = []
        while True:
            count = r.read_long()
            if count == 0:
                break
            if count < 0:  # block with byte-size prefix
                count = -count
                r.read_long()
            for _ in range(count):
                out.append(_read_datum(r, schema["items"], registry))
        return out
    if t == "map":
        out = {}
        while True:
            count = r.read_long()
            if count == 0:
                break
            if count < 0:
                count = -count
                r.read_long()
            for _ in range(count):
                key = r.read_bytes().decode("utf-8")
                out[key] = _read_datum(r, schema["values"], registry)
        return out
    raise ValueError(f"unsupported avro type: {t!r}")


def _union_branch(value: Any, branches: List[Schema]) -> int:
    """Pick the first union branch whose type matches the python value."""
    for i, b in enumerate(branches):
        t = _schema_type(b)
        if value is None and t == "null":
            return i
        if isinstance(value, bool) and t == "boolean":
            return i
        if isinstance(value, int) and not isinstance(value, bool) \
                and t in ("int", "long"):
            return i
        if isinstance(value, float) and t in ("float", "double"):
            return i
        if isinstance(value, str) and t in ("string", "enum"):
            return i
        if isinstance(value, (bytes, bytearray)) and t in ("bytes", "fixed"):
            return i
        if isinstance(value, dict) and t in ("record", "map"):
            return i
        if isinstance(value, list) and t == "array":
            return i
    raise ValueError(f"no avro union branch matches {type(value).__name__}")


def _write_datum(w: _Writer, value: Any, schema: Schema,
                 registry: Dict[str, Schema]) -> None:
    if isinstance(schema, str) and schema not in PRIMITIVES:
        schema = registry[schema]
    t = _schema_type(schema)
    if t == "null":
        return
    if t == "boolean":
        w.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        w.write_long(value)
    elif t == "float":
        w.write(struct.pack("<f", value))
    elif t == "double":
        w.write(struct.pack("<d", value))
    elif t == "bytes":
        w.write_bytes(bytes(value))
    elif t == "string":
        w.write_bytes(value.encode("utf-8"))
    elif t == "union":
        branches = schema if isinstance(schema, list) else schema["type"]
        idx = _union_branch(value, branches)
        w.write_long(idx)
        _write_datum(w, value, branches[idx], registry)
    elif t == "record":
        for f in schema["fields"]:
            _write_datum(w, value[f["name"]], f["type"], registry)
    elif t == "enum":
        w.write_long(schema["symbols"].index(value))
    elif t == "fixed":
        if len(value) != schema["size"]:
            raise ValueError("avro fixed size mismatch")
        w.write(bytes(value))
    elif t == "array":
        if value:
            w.write_long(len(value))
            for item in value:
                _write_datum(w, item, schema["items"], registry)
        w.write_long(0)
    elif t == "map":
        if value:
            w.write_long(len(value))
            for key, item in value.items():
                w.write_bytes(key.encode("utf-8"))
                _write_datum(w, item, schema["values"], registry)
        w.write_long(0)
    else:
        raise ValueError(f"unsupported avro type: {t!r}")


def decode(payload: bytes, schema: Union[str, bytes, Schema]) -> Any:
    """Decode one binary-encoded datum against a schema."""
    schema = parse_schema(schema)
    registry: Dict[str, Schema] = {}
    _named_types(schema, registry)
    r = _Reader(payload)
    return _read_datum(r, schema, registry)


def encode(value: Any, schema: Union[str, bytes, Schema]) -> bytes:
    """Binary-encode one datum against a schema."""
    schema = parse_schema(schema)
    registry: Dict[str, Schema] = {}
    _named_types(schema, registry)
    w = _Writer()
    _write_datum(w, value, schema, registry)
    return w.getvalue()
