"""V2 (KFServing/Triton "Predict Protocol - Version 2") inference protocol.

Implements the JSON tensor format of the reference spec
(reference docs/predict-api/v2/required_api.md, grpc_predict_v2.proto):

    $inference_request = {
      "id": $string #optional, "parameters": $parameters #optional,
      "inputs": [ $request_input, ... ],
      "outputs": [ $request_output, ... ] #optional
    }
    $request_input = {"name", "shape", "datatype", "parameters"#opt, "data"}

Tensors are encoded/decoded to numpy with an explicit dtype table, including
BF16 (served models are bfloat16 on TPU; JSON carries floats either way).

Also implements the HTTP **binary data extension** (the HTTP twin of the
proto's `raw_input_contents`, reference grpc_predict_v2.proto:664-676):
body = JSON header + concatenated raw tensor bytes, split by the
`Inference-Header-Content-Length` header; each binary input declares
`parameters: {"binary_data_size": N}` and omits "data".  On a one-core
serving host this is the difference between ~5ms of JSON number parsing
per image and a memcpy — the wire format for TPU-bound dense tensors.
"""

from typing import Any, Dict, List, Optional

import numpy as np

from kfserving_tpu.protocol.errors import InvalidInput

# Datatype table from the V2 spec ("Tensor Data Types" section of
# reference docs/predict-api/v2/required_api.md).
DTYPES_TO_NUMPY = {
    "BOOL": np.bool_,
    "UINT8": np.uint8,
    "UINT16": np.uint16,
    "UINT32": np.uint32,
    "UINT64": np.uint64,
    "INT8": np.int8,
    "INT16": np.int16,
    "INT32": np.int32,
    "INT64": np.int64,
    "FP16": np.float16,
    "FP32": np.float32,
    "FP64": np.float64,
    "BYTES": np.object_,
}

NUMPY_TO_DTYPES = {
    np.dtype(np.bool_): "BOOL",
    np.dtype(np.uint8): "UINT8",
    np.dtype(np.uint16): "UINT16",
    np.dtype(np.uint32): "UINT32",
    np.dtype(np.uint64): "UINT64",
    np.dtype(np.int8): "INT8",
    np.dtype(np.int16): "INT16",
    np.dtype(np.int32): "INT32",
    np.dtype(np.int64): "INT64",
    np.dtype(np.float16): "FP16",
    np.dtype(np.float32): "FP32",
    np.dtype(np.float64): "FP64",
}


def _numpy_dtype(datatype: str):
    if datatype == "BF16":
        # ml_dtypes ships with jax; BF16 rides JSON as plain numbers.
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return DTYPES_TO_NUMPY[datatype]
    except KeyError:
        raise InvalidInput(f"Unsupported datatype {datatype}")


def datatype_of(arr: np.ndarray) -> str:
    dt = np.dtype(arr.dtype)
    if dt.name == "bfloat16":
        return "BF16"
    if dt.kind in ("U", "S", "O"):
        return "BYTES"
    try:
        return NUMPY_TO_DTYPES[dt]
    except KeyError:
        raise InvalidInput(f"Unsupported numpy dtype {dt}")


def frame_raw_bytes(elems) -> bytes:
    """V2 raw BYTES framing: 4-byte little-endian length before each
    element (shared by HTTP binary extension and gRPC raw contents)."""
    import struct

    out = []
    for e in elems:
        b = (e if isinstance(e, bytes)
             else e.encode() if isinstance(e, str) else bytes(e))
        out.append(struct.pack("<I", len(b)) + b)
    return b"".join(out)


def decode_raw_bytes(raw: bytes) -> List[bytes]:
    """V2 raw BYTES framing: 4-byte little-endian length before each
    element (required_api.md binary data / raw_input_contents)."""
    import struct

    out: List[bytes] = []
    offset, n = 0, len(raw)
    while offset < n:
        if offset + 4 > n:
            raise InvalidInput("truncated raw BYTES tensor")
        (length,) = struct.unpack_from("<I", raw, offset)
        offset += 4
        if offset + length > n:
            raise InvalidInput("truncated raw BYTES element")
        out.append(raw[offset:offset + length])
        offset += length
    return out


class InferInput:
    """One named input tensor of a V2 inference request."""

    def __init__(self, name: str, shape: List[int], datatype: str,
                 data: Any, parameters: Optional[Dict] = None,
                 raw: Optional[bytes] = None):
        self.name = name
        self.shape = list(shape)
        self.datatype = datatype
        self.data = data
        self.parameters = parameters or {}
        self.raw = raw

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InferInput":
        required = ("name", "shape", "datatype")
        for field in required:
            if field not in d:
                raise InvalidInput(f'Input tensor missing required field "{field}"')
        params = d.get("parameters") or {}
        if "data" not in d and not params.get("binary_data_size"):
            raise InvalidInput('Input tensor missing required field "data"')
        if not isinstance(d["shape"], list):
            raise InvalidInput('Input tensor "shape" must be a list')
        return cls(d["name"], d["shape"], d["datatype"], d.get("data"),
                   params)

    @property
    def binary_data_size(self) -> int:
        return int(self.parameters.get("binary_data_size") or 0)

    def as_numpy(self) -> np.ndarray:
        dtype = _numpy_dtype(self.datatype)
        if self.raw is not None:
            if self.datatype == "BYTES":
                arr = np.array(decode_raw_bytes(self.raw),
                               dtype=np.object_)
            else:
                try:
                    arr = np.frombuffer(self.raw, dtype=dtype)
                except ValueError as e:
                    raise InvalidInput(
                        f"Input {self.name}: binary data of "
                        f"{len(self.raw)} bytes does not fit datatype "
                        f"{self.datatype}: {e}")
        elif self.data is None:
            # binary_data_size declared but the request carried no
            # binary body (plain JSON POST) — a client error, not a
            # server crash.
            raise InvalidInput(
                f"Input {self.name} declares binary_data_size but the "
                f"request has no binary body (missing "
                f"Inference-Header-Content-Length?)")
        elif self.datatype == "BYTES":
            arr = np.array(self.data, dtype=np.object_)
        else:
            arr = np.asarray(self.data, dtype=dtype)
        try:
            return arr.reshape(self.shape)
        except ValueError:
            raise InvalidInput(
                f"Input {self.name}: data of size {arr.size} does not match "
                f"shape {self.shape}")

    def to_dict(self) -> Dict[str, Any]:
        out = {"name": self.name, "shape": self.shape,
               "datatype": self.datatype, "data": self.data}
        if self.parameters:
            out["parameters"] = self.parameters
        return out


class InferRequest:
    """A decoded V2 inference request."""

    def __init__(self, inputs: List[InferInput], id: Optional[str] = None,
                 parameters: Optional[Dict] = None,
                 outputs: Optional[List[Dict]] = None):
        self.inputs = inputs
        self.id = id
        self.parameters = parameters or {}
        self.outputs = outputs or []

    @classmethod
    def from_dict(cls, body: Dict[str, Any]) -> "InferRequest":
        if not isinstance(body, dict):
            raise InvalidInput("V2 inference request must be a JSON object")
        if "inputs" not in body or not isinstance(body["inputs"], list):
            raise InvalidInput('Expected "inputs" to be a list')
        inputs = [InferInput.from_dict(i) for i in body["inputs"]]
        return cls(inputs, body.get("id"), body.get("parameters"),
                   body.get("outputs"))

    @classmethod
    def from_binary(cls, body: bytes, header_length: int) -> "InferRequest":
        """Decode a binary-extension request: JSON header in
        body[:header_length], then each binary input's raw bytes in
        input order (the HTTP form of raw_input_contents,
        grpc_predict_v2.proto:664-676)."""
        import json as _json

        if header_length <= 0 or header_length > len(body):
            raise InvalidInput(
                f"Inference-Header-Content-Length {header_length} out of "
                f"range for body of {len(body)} bytes")
        try:
            header = _json.loads(body[:header_length])
        except ValueError as e:
            raise InvalidInput(f"invalid V2 binary header: {e}")
        req = cls.from_dict(header)
        offset = header_length
        for inp in req.inputs:
            size = inp.binary_data_size
            if not size:
                continue
            if offset + size > len(body):
                raise InvalidInput(
                    f"binary data for input {inp.name!r} overruns the "
                    f"request body")
            # Zero-copy view of the request buffer; np.frombuffer in
            # as_numpy never touches the bytes again.
            inp.raw = body[offset:offset + size]
            offset += size
        if offset != len(body):
            raise InvalidInput(
                f"{len(body) - offset} trailing bytes after the last "
                f"binary input")
        return req

    def named_numpy(self) -> Dict[str, np.ndarray]:
        return {i.name: i.as_numpy() for i in self.inputs}

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"inputs": [i.to_dict() for i in self.inputs]}
        if self.id is not None:
            out["id"] = self.id
        if self.parameters:
            out["parameters"] = self.parameters
        if self.outputs:
            out["outputs"] = self.outputs
        return out


def make_binary_request(tensors: Dict[str, np.ndarray],
                        id: Optional[str] = None,
                        binary_output: bool = False
                        ) -> "tuple[bytes, int]":
    """Client-side encoder for the binary extension: returns
    (body, header_length) ready to POST with the
    Inference-Header-Content-Length header set.  binary_output=True
    asks the server to return outputs as raw bytes too."""
    import json as _json

    inputs = []
    raws = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        datatype = datatype_of(arr)
        if datatype == "BYTES":
            # Element framing required by decode_raw_bytes (raw
            # .tobytes() of S/object arrays would misparse server-side).
            raw = frame_raw_bytes(
                e if isinstance(e, bytes) else str(e).encode()
                for e in arr.ravel())
        else:
            raw = arr.tobytes()
        raws.append(raw)
        inputs.append({
            "name": name, "shape": list(arr.shape),
            "datatype": datatype,
            "parameters": {"binary_data_size": len(raw)},
        })
    header: Dict[str, Any] = {"inputs": inputs}
    if binary_output:
        header["parameters"] = {"binary_data_output": True}
    if id is not None:
        header["id"] = id
    hbytes = _json.dumps(header).encode()
    return hbytes + b"".join(raws), len(hbytes)


INFERENCE_HEADER_CONTENT_LENGTH = "inference-header-content-length"


def encode_binary_response(response: Dict[str, Any]
                           ) -> "tuple[bytes, int]":
    """Binary-extension response encoding: outputs' data ships as raw
    bytes after the JSON header (the response-side twin of
    raw_output_contents, grpc_predict_v2.proto:773).  Returns
    (body, header_length)."""
    import json as _json

    header = dict(response)
    outputs = []
    raws = []
    for out in response.get("outputs", []):
        data = out.get("data")
        dtype = _numpy_dtype(out["datatype"])
        if out["datatype"] == "BYTES":
            raw = frame_raw_bytes(
                e if isinstance(e, bytes) else str(e).encode()
                for e in np.asarray(data, np.object_).ravel())
        else:
            raw = np.ascontiguousarray(
                np.asarray(data, dtype=dtype)).tobytes()
        entry = {k: v for k, v in out.items() if k != "data"}
        params = dict(entry.get("parameters") or {})
        params["binary_data_size"] = len(raw)
        entry["parameters"] = params
        outputs.append(entry)
        raws.append(raw)
    header["outputs"] = outputs
    hbytes = _json.dumps(header).encode()
    return hbytes + b"".join(raws), len(hbytes)


def decode_binary_response(body: bytes,
                           header_length: int) -> Dict[str, Any]:
    """Client-side decode of a binary-extension response: outputs' data
    come back as numpy arrays."""
    import json as _json

    if header_length <= 0 or header_length > len(body):
        raise InvalidInput(
            f"response header length {header_length} out of range")
    resp = _json.loads(body[:header_length])
    offset = header_length
    for out in resp.get("outputs", []):
        size = int((out.get("parameters") or {})
                   .get("binary_data_size") or 0)
        if not size:
            continue
        if offset + size > len(body):
            # A truncated response must fail cleanly, not as a numpy
            # reshape error / silently short BYTES list (mirrors
            # InferRequest.from_binary's overrun check).
            raise InvalidInput(
                f"binary output {out.get('name')!r} overruns the "
                f"response body: need {offset + size} bytes, "
                f"have {len(body)}")
        raw = body[offset:offset + size]
        offset += size
        if out["datatype"] == "BYTES":
            out["data"] = decode_raw_bytes(raw)
        else:
            out["data"] = np.frombuffer(
                raw, dtype=_numpy_dtype(out["datatype"])
            ).reshape(out["shape"])
    return resp


def tensor_to_output(name: str, arr: np.ndarray) -> Dict[str, Any]:
    """Encode a numpy array as a V2 response output tensor."""
    arr = np.asarray(arr)
    datatype = datatype_of(arr)
    if datatype == "BF16":
        data = arr.astype(np.float32).ravel().tolist()
    elif datatype == "BYTES":
        data = [x.decode() if isinstance(x, bytes) else str(x)
                for x in arr.ravel().tolist()]
    else:
        data = arr.ravel().tolist()
    return {"name": name, "shape": list(arr.shape), "datatype": datatype,
            "data": data}


def make_response(model_name: str, outputs: Dict[str, np.ndarray],
                  id: Optional[str] = None,
                  model_version: Optional[str] = None) -> Dict[str, Any]:
    resp: Dict[str, Any] = {
        "model_name": model_name,
        "outputs": [tensor_to_output(k, v) for k, v in outputs.items()],
    }
    if model_version is not None:
        resp["model_version"] = model_version
    if id is not None:
        resp["id"] = id
    return resp
