"""Native codec loader + pure-Python fallback.

The C extension (csrc/tensorjson.c) parses dense V1 predict bodies into
contiguous float32 buffers and dumps prediction tensors back to JSON in
one pass.  This wrapper:

- loads `_tensorjson` from csrc/ when built (csrc/setup.py), else exposes
  the same API in pure Python;
- returns numpy views over the parsed buffer (zero-copy reshape).

Fast path eligibility is decided by the caller (server/dataplane): dense
numeric bodies only; anything else (dicts, strings, V2 tensor objects,
CloudEvents) takes the json.loads route unchanged.
"""

import json
import logging
import os
import sys
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger("kfserving_tpu.native")

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")

_native = None


def _load():
    global _native
    if _native is not None:
        return _native
    if _CSRC not in sys.path:
        sys.path.insert(0, _CSRC)
    try:
        import _tensorjson  # type: ignore

        # API probe: parse_v1 must report extra top-level keys (5-tuple)
        # AND accept the dtype hint (2-arg form).  A stale prebuilt .so
        # with either older API would drop keys or raise TypeError on
        # every hinted call, so refuse it.
        probe = _tensorjson.parse_v1(b'{"instances": [1], "x": 1}',
                                     "u1")
        if len(probe) != 5:
            logger.warning(
                "stale _tensorjson extension (no extra-keys flag); "
                "using pure-Python codec — rebuild with native.build(force=True)")
            _native = False
        else:
            _native = _tensorjson
            logger.info("native tensorjson codec loaded")
    except TypeError:
        logger.warning(
            "stale _tensorjson extension (no dtype-hint arg); using "
            "pure-Python codec — rebuild with native.build(force=True)")
        _native = False
    except (ImportError, ValueError):
        _native = False
    return _native


def build(force: bool = False) -> bool:
    """Compile the extension in-place (used by tests/deploy scripts)."""
    import glob
    import subprocess

    if not force and glob.glob(os.path.join(_CSRC, "_tensorjson*.so")):
        return True
    try:
        subprocess.run(
            [sys.executable, os.path.join(_CSRC, "setup.py")],
            cwd=_CSRC, check=True, capture_output=True, timeout=120)
        global _native
        _native = None  # re-probe
        return bool(_load())
    except Exception as e:
        logger.warning("native build failed: %s", e)
        return False


def available() -> bool:
    return bool(_load())


_DTYPES = {"u1": np.uint8, "i4": np.int32, "f4": np.float32}


def parse_v1(body: bytes, hint: Optional[str] = None
             ) -> Optional[Tuple[np.ndarray, str]]:
    """Parse a dense V1 body -> (array, key) or None if ineligible.

    hint="u1" (the served model's declared uint8 wire dtype) parses
    integer image bodies straight into uint8 — no int32 intermediate,
    no astype copy downstream.  The hint is advisory: values outside
    [0, 255] emit the normal i4/f4 and the model's own cast handles it.

    Never raises for non-dense bodies: the caller falls back to
    json.loads.
    """
    mod = _load()
    if mod:
        try:
            out = mod.parse_v1(body, hint)
        except ValueError:
            return None
        data, shape, key, dtype, extra = out
        if extra:
            # Body carries other top-level keys (parameters,
            # signature_name, custom fields): a {key: arr} dict would
            # silently drop them before model.preprocess, so fall back
            # to the full json.loads decode.
            return None
        arr = np.frombuffer(data, dtype=_DTYPES[dtype]).reshape(shape)
        return arr, key
    return _parse_v1_py(body, hint)


def _parse_v1_py(body: bytes, hint: Optional[str] = None
                 ) -> Optional[Tuple[np.ndarray, str]]:
    """Pure-Python fallback with identical eligibility rules."""
    try:
        obj = json.loads(body)
    except ValueError:
        return None
    if not isinstance(obj, dict):
        return None
    key = ("instances" if "instances" in obj
           else "inputs" if "inputs" in obj else None)
    if key is None or not isinstance(obj[key], list):
        return None
    if len(obj) > 1:
        # Extra top-level keys must survive to model.preprocess; the
        # {key: arr} fast-path shape would drop them.
        return None
    try:
        arr = np.asarray(obj[key])
    except (ValueError, TypeError):
        return None
    if arr.ndim == 0 or arr.dtype == object:
        return None
    if np.issubdtype(arr.dtype, np.integer):
        if arr.size and (np.abs(arr) > np.iinfo(np.int32).max).any():
            arr = arr.astype(np.float32)
        elif hint == "u1" and (not arr.size or
                               (arr.min() >= 0 and arr.max() <= 255)):
            arr = arr.astype(np.uint8)
        else:
            arr = arr.astype(np.int32)
    elif np.issubdtype(arr.dtype, np.floating):
        arr = arr.astype(np.float32)
    else:
        return None
    return arr, key


def dump_f32(arr: np.ndarray) -> bytes:
    """Serialize a float tensor as a JSON array (bytes)."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    mod = _load()
    if mod:
        return mod.dump_f32(arr.tobytes(), tuple(arr.shape))
    return json.dumps(arr.tolist()).encode()


def dump_response(body) -> Optional[bytes]:
    """Fast-serialize `{"predictions": <float32 ndarray>}` responses.

    Returns None when ineligible (other keys, non-array, non-float32 —
    integer class labels must round-trip as ints, not "1.0").
    """
    if not isinstance(body, dict) or set(body) != {"predictions"}:
        return None
    arr = body["predictions"]
    if not isinstance(arr, np.ndarray) or arr.dtype != np.float32 \
            or arr.ndim == 0:
        return None
    return b'{"predictions": ' + dump_f32(arr) + b"}"
