"""Storage-initializer entrypoint (reference
python/storage-initializer/scripts/initializer-entrypoint:1-14):

    python -m kfserving_tpu.storage <src-uri> <dest-dir>

Downloads a model artifact to a local directory before the serving
process starts — the init-container role, usable standalone or from
any process supervisor.
"""

import logging
import sys

from kfserving_tpu.storage import Storage

logging.basicConfig(level=logging.INFO)

if __name__ == "__main__":
    if len(sys.argv) != 3:
        print("usage: python -m kfserving_tpu.storage <src-uri> "
              "<dest-dir>", file=sys.stderr)
        sys.exit(2)
    src, dest = sys.argv[1], sys.argv[2]
    logging.info("Initializing, args: src_uri [%s] dest_path [%s]",
                 src, dest)
    Storage.download(src, dest)
