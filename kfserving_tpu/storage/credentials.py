"""Credential injection for model-artifact storage.

Re-expresses the reference credentials builder (reference
pkg/credentials/service_account_credentials.go:64+ and the
{s3,gcs,azure,https}/*_secret.go sub-packages): secrets attached to a
service account become environment variables / credential files on the
serving replica, so `Storage.download` finds them the same way the
reference's storage-initializer container does.

Without Kubernetes the secret store is a JSON file (the cluster
operator's analogue of Secret objects):

    {
      "serviceAccounts": {"default": ["my-s3", "my-gcs"]},
      "secrets": {
        "my-s3": {
          "type": "s3",
          "data": {"accessKeyId": "...", "secretAccessKey": "..."},
          "annotations": {
            "serving.kfserving.io/s3-endpoint": "minio:9000",
            "serving.kfserving.io/s3-usehttps": "0",
            "serving.kfserving.io/s3-region": "us-east-1"
          }
        },
        "my-gcs":   {"type": "gcs", "data": {"gcloud": {...sa json...}}},
        "my-azure": {"type": "azure", "data": {"subscriptionId": "...",
                     "tenantId": "...", "clientId": "...",
                     "clientSecret": "..."}},
        "my-https": {"type": "https", "data": {"host": "models.example",
                     "headers": {"Authorization": "Bearer ..."}}}
      }
    }

`build_env(service_account)` returns the env mapping (writing the GCS
JSON to disk); orchestrators inject it into replica processes
(subprocess env / in-process os.environ), mirroring the reference's
env+volume injection into containers.
"""

import json
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("kfserving_tpu.credentials")

# Annotation keys (reference pkg/credentials/s3/s3_secret.go constants).
S3_ENDPOINT_ANNOTATION = "serving.kfserving.io/s3-endpoint"
S3_USEHTTPS_ANNOTATION = "serving.kfserving.io/s3-usehttps"
S3_REGION_ANNOTATION = "serving.kfserving.io/s3-region"
S3_VERIFYSSL_ANNOTATION = "serving.kfserving.io/s3-verifyssl"

# File name matches the reference configmap default
# (gcsCredentialFileName, service_account_credentials.go:39-62).
DEFAULT_GCS_FILE_NAME = "gcloud-application-credentials.json"


@dataclass
class Secret:
    name: str
    type: str  # s3 | gcs | azure | https
    data: Dict = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


class CredentialStore:
    """Service-account -> secrets registry (the Secret-object store)."""

    def __init__(self, service_accounts: Optional[Dict[str, List[str]]]
                 = None,
                 secrets: Optional[Dict[str, Secret]] = None,
                 gcs_file_name: str = DEFAULT_GCS_FILE_NAME,
                 creds_dir: Optional[str] = None):
        self.service_accounts = service_accounts or {}
        self.secrets = secrets or {}
        self.gcs_file_name = gcs_file_name
        self._creds_dir = creds_dir

    @classmethod
    def load(cls, path: Optional[str],
             gcs_file_name: str = DEFAULT_GCS_FILE_NAME
             ) -> "CredentialStore":
        if not path:
            return cls(gcs_file_name=gcs_file_name)
        if not os.path.exists(path):
            # A configured-but-absent store starts empty; the first
            # client-side registration creates it (control/api.py).
            return cls(gcs_file_name=gcs_file_name)
        with open(path) as f:
            data = json.load(f)
        return cls.from_dict(data, gcs_file_name=gcs_file_name)

    @classmethod
    def from_dict(cls, data: Dict,
                  gcs_file_name: str = DEFAULT_GCS_FILE_NAME
                  ) -> "CredentialStore":
        secrets = {}
        for name, entry in (data.get("secrets") or {}).items():
            secrets[name] = Secret(
                name=name,
                type=entry.get("type", ""),
                data=entry.get("data") or {},
                annotations=entry.get("annotations") or {})
        return cls(service_accounts=dict(
                       data.get("serviceAccounts") or {}),
                   secrets=secrets, gcs_file_name=gcs_file_name)

    # -- registration (SDK creds_utils server side) -------------------------
    def add_secret(self, type: str, data: Dict,
                   annotations: Optional[Dict[str, str]] = None,
                   name: Optional[str] = None) -> str:
        """Create-or-replace a secret; generates a name when none given
        (reference creds_utils.create_secret uses generateName
        'kfserving-secret-', api/creds_utils.py:144-167)."""
        if not name:
            n = len(self.secrets)
            while f"kfserving-secret-{n}" in self.secrets:
                n += 1
            name = f"kfserving-secret-{n}"
        self.secrets[name] = Secret(name=name, type=type, data=dict(data),
                                    annotations=dict(annotations or {}))
        return name

    def attach(self, service_account: str, secret_name: str) -> None:
        """Attach a secret to a service account, creating the account if
        absent (reference set_service_account create-or-patch,
        api/creds_utils.py:170-180)."""
        if secret_name not in self.secrets:
            raise KeyError(f"secret {secret_name!r} not found")
        attached = self.service_accounts.setdefault(service_account, [])
        if secret_name not in attached:
            attached.append(secret_name)

    def remove_secret(self, name: str) -> None:
        if name not in self.secrets:
            raise KeyError(f"secret {name!r} not found")
        del self.secrets[name]
        for attached in self.service_accounts.values():
            if name in attached:
                attached.remove(name)

    def to_dict(self) -> Dict:
        return {
            "serviceAccounts": {k: list(v)
                                for k, v in self.service_accounts.items()},
            "secrets": {
                name: {"type": s.type, "data": s.data,
                       "annotations": s.annotations}
                for name, s in self.secrets.items()
            },
        }

    def save(self, path: str) -> None:
        """Persist the store (atomic replace; the file holds live
        credentials, so 0600 like the GCS key file)."""
        self.write_snapshot(path, self.to_dict())

    @staticmethod
    def write_snapshot(path: str, data: Dict) -> None:
        """Write an already-taken `to_dict()` snapshot.  Split from
        `save` so async callers can snapshot on the event loop (cheap,
        consistent) and ship only the disk write to an executor —
        handing the live store to a writer thread would race its dict
        iteration against loop-side mutations."""
        tmp = f"{path}.tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=2)
        os.replace(tmp, path)

    # -- builder (CreateSecretVolumeAndEnv equivalent) ----------------------
    def build_env(self, service_account: str = "default"
                  ) -> Dict[str, str]:
        """Env mapping for a replica running under `service_account`.

        GCS service-account JSON is written to a credentials dir and
        referenced by GOOGLE_APPLICATION_CREDENTIALS (the reference
        mounts the secret as a volume at the same file name).
        """
        env: Dict[str, str] = {}
        for secret_name in self.service_accounts.get(service_account, []):
            secret = self.secrets.get(secret_name)
            if secret is None:
                logger.warning("secret %s attached to %s not found",
                               secret_name, service_account)
                continue
            builder = getattr(self, f"_build_{secret.type}", None)
            if builder is None:
                logger.warning("unknown secret type %r on %s",
                               secret.type, secret_name)
                continue
            builder(secret, env, service_account)
        return env

    def _build_s3(self, secret: Secret, env: Dict[str, str],
                  account: str = "default") -> None:
        """Reference s3_secret.go: key id/secret from data, endpoint/
        region/SSL knobs from annotations."""
        if "accessKeyId" in secret.data:
            env["AWS_ACCESS_KEY_ID"] = str(secret.data["accessKeyId"])
        if "secretAccessKey" in secret.data:
            env["AWS_SECRET_ACCESS_KEY"] = str(
                secret.data["secretAccessKey"])
        ann = secret.annotations
        if S3_ENDPOINT_ANNOTATION in ann:
            endpoint = ann[S3_ENDPOINT_ANNOTATION]
            env["S3_ENDPOINT"] = endpoint
            use_https = ann.get(S3_USEHTTPS_ANNOTATION, "1")
            env["S3_USE_HTTPS"] = use_https
            scheme = "https" if use_https not in ("0", "false") else "http"
            env["AWS_ENDPOINT_URL"] = f"{scheme}://{endpoint}"
        if S3_REGION_ANNOTATION in ann:
            env["AWS_REGION"] = ann[S3_REGION_ANNOTATION]
        if S3_VERIFYSSL_ANNOTATION in ann:
            env["S3_VERIFY_SSL"] = ann[S3_VERIFYSSL_ANNOTATION]

    def _build_gcs(self, secret: Secret, env: Dict[str, str],
                   account: str = "default") -> None:
        payload = secret.data.get("gcloud")
        if payload is None:
            logger.warning("gcs secret %s has no 'gcloud' key",
                           secret.name)
            return
        if self._creds_dir is None:
            self._creds_dir = tempfile.mkdtemp(prefix="kfs-creds-")
        # Per-account subdirectory: two service accounts must never
        # share (and overwrite) one key file.
        account_dir = os.path.join(self._creds_dir, account)
        os.makedirs(account_dir, exist_ok=True)
        path = os.path.join(account_dir, self.gcs_file_name)
        with open(path, "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f)
        os.chmod(path, 0o600)
        env["GOOGLE_APPLICATION_CREDENTIALS"] = path

    def _build_azure(self, secret: Secret, env: Dict[str, str],
                     account: str = "default") -> None:
        """Reference azure_secret.go: service-principal env quartet."""
        mapping = {"subscriptionId": "AZ_SUBSCRIPTION_ID",
                   "tenantId": "AZ_TENANT_ID",
                   "clientId": "AZ_CLIENT_ID",
                   "clientSecret": "AZ_CLIENT_SECRET"}
        for key, var in mapping.items():
            if key in secret.data:
                env[var] = str(secret.data[key])

    def _build_https(self, secret: Secret, env: Dict[str, str],
                     account: str = "default") -> None:
        """Per-host request headers for http(s) artifact pulls (reference
        https_secret.go builds header env from the secret).

        All hosts ride ONE env var holding a host->headers JSON map:
        mangling hosts into env-var names is not injective
        ('models-example.com' vs 'models.example.com' would collide and
        leak one host's Authorization header to the other).
        """
        host = secret.data.get("host")
        headers = secret.data.get("headers") or {}
        if not host:
            logger.warning("https secret %s has no 'host'", secret.name)
            return
        try:
            current = json.loads(env.get(HTTPS_HEADERS_ENV, "{}"))
        except ValueError:
            current = {}
        current[host] = headers
        env[HTTPS_HEADERS_ENV] = json.dumps(current)


HTTPS_HEADERS_ENV = "KFS_HTTPS_HEADERS"


def https_headers_for(uri: str,
                      env: Optional[Dict[str, str]] = None
                      ) -> Dict[str, str]:
    """Headers a https secret configured for this URI's host (consumed
    by Storage._download_from_uri).  Matches the exact netloc first,
    then the bare hostname (secrets usually omit the port)."""
    from urllib.parse import urlparse

    env = env if env is not None else os.environ
    raw = env.get(HTTPS_HEADERS_ENV)
    if not raw:
        return {}
    try:
        table = json.loads(raw)
    except ValueError:
        logger.warning("invalid headers JSON in %s", HTTPS_HEADERS_ENV)
        return {}
    parsed = urlparse(uri)
    for candidate in (parsed.netloc, parsed.hostname):
        entry = table.get(candidate)
        if isinstance(entry, dict):
            return {str(k): str(v) for k, v in entry.items()}
    return {}
