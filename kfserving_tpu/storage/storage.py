"""Model artifact storage: `Storage.download(uri, out_dir)` dispatch matrix.

Re-implements the reference storage layer (reference python/kfserving/
kfserving/storage.py:42-283) with the same URI scheme dispatch:

- `gs://`      Google Cloud Storage (anonymous fallback, storage.py:104-134)
- `s3://`      S3-compatible (env-configured endpoint, storage.py:82-101)
- `azure://`   (https://<account>.blob.core.windows.net/..., storage.py:137-204)
- `file://`    local symlink (storage.py:206-225)
- `http(s)://` download, unpacking zip/tar/tgz (storage.py:227-271)
- `pvc://`     mounted volume path
- local path   passthrough
- `mms://`     multi-model passthrough marker (storage.py:69-72)

Cloud SDKs are optional: providers raise a clear error when the client
library is absent (this environment is hermetic).  Downloads are idempotent
via `SUCCESS.<sha256(uri)>` marker files, the same scheme the reference Go
agent uses to skip completed pulls across restarts
(reference pkg/agent/downloader.go:42-75).

Remote downloads retry with exponential backoff (`KFS_STORAGE_RETRY_*`
env knobs; markers make replays idempotent — the TensorFlow-Serving
retried-model-load discipline), and the `storage.download` fault site
lets chaos tests inject failures exactly where a flaky object store
would produce them.

Content integrity: when the pulled artifact ships digests — per-file
`<name>.sha256` siblings or a `SHA256SUMS`/`checksums.sha256`
manifest — every covered file's sha256 is verified after the pull.
A mismatch deletes the corrupt file and raises a connection-class
error, so the retry policy re-pulls instead of the marker trusting a
corrupt payload forever (the marker keys only on the URI and is
written strictly after verification).
"""

import glob
import gzip
import hashlib
import json
import logging
import os
import re
import shutil
import tarfile
import tempfile
import zipfile
from typing import Optional
from urllib.parse import urlparse
from urllib.request import Request as UrlRequest
from urllib.request import urlopen

logger = logging.getLogger("kfserving_tpu.storage")

_GCS_PREFIX = "gs://"
_S3_PREFIX = "s3://"
_AZURE_BLOB_RE = r"https://(.+?)\.blob\.core\.windows\.net/(.+)"
_LOCAL_PREFIX = "file://"
_PVC_PREFIX = "pvc://"
_MMS_PREFIX = "mms://"
_HTTP_PREFIX = ("http://", "https://")

_ARCHIVE_SUFFIXES = (".tar", ".tgz", ".tar.gz", ".zip", ".gz")


_MANIFEST_NAMES = ("SHA256SUMS", "checksums.sha256")


def _success_marker(uri: str, out_dir: str) -> str:
    digest = hashlib.sha256(uri.encode("utf-8")).hexdigest()
    return os.path.join(out_dir, f"SUCCESS.{digest}")


class StorageIntegrityError(ConnectionError):
    """Artifact content failed its shipped digest.  Subclasses
    ConnectionError on purpose: the retry policy classifies it
    transient, so a corrupted transfer re-pulls with backoff (the
    corrupt file is already deleted) instead of failing the replica
    terminally on one flipped bit."""


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _digest_expectations(out_dir: str):
    """(file path, expected hex digest) pairs declared by the artifact:
    per-file `<name>.sha256` siblings (first hex token of the file —
    both bare-digest and coreutils `digest  name` layouts parse), and
    manifest files with `digest  relative/path` lines."""
    expectations = []
    # Coreutils manifest line: digest, separator, name (binary-mode
    # names lead with '*'; names may contain spaces).
    manifest_line = re.compile(r"^([0-9a-fA-F]{64})[ \t]+\*?(.+)$")
    # followlinks=False: a symlinked artifact dir (local passthrough)
    # is never verified here, and a payload shipping a self-referential
    # link must not walk the verifier into a cycle.
    for root, _, files in os.walk(out_dir):
        for fname in files:
            path = os.path.join(root, fname)
            if fname in _MANIFEST_NAMES:
                try:
                    with open(path) as f:
                        for line in f:
                            m = manifest_line.match(line.rstrip("\n"))
                            if m is None:
                                continue
                            target = os.path.normpath(
                                os.path.join(root, m.group(2)))
                            # Containment: a hostile manifest naming
                            # '../../etc/x' or an absolute path must
                            # not make the verifier hash — or on
                            # mismatch DELETE — anything outside the
                            # artifact dir.
                            if os.path.isabs(m.group(2)) or \
                                    os.path.commonpath(
                                        [os.path.abspath(target),
                                         os.path.abspath(out_dir)]) != \
                                    os.path.abspath(out_dir):
                                logger.warning(
                                    "ignoring digest for %r: escapes "
                                    "the artifact dir", m.group(2))
                                continue
                            expectations.append((target,
                                                 m.group(1).lower()))
                except OSError:
                    continue
            elif fname.endswith(".sha256"):
                try:
                    with open(path) as f:
                        head = f.read(1024).split()
                except OSError:
                    continue
                if head and re.fullmatch(r"[0-9a-fA-F]{64}", head[0]):
                    expectations.append((path[:-len(".sha256")],
                                         head[0].lower()))
    return expectations


def verify_integrity(out_dir: str) -> int:
    """Verify every digest the artifact ships; returns how many files
    were checked.  On mismatch the corrupt file is DELETED (so the
    retried pull rewrites it from the source instead of the bad bytes
    surviving a partial re-pull) and StorageIntegrityError raises.  A
    declared-but-missing file is the same condition: the payload is
    incomplete."""
    checked = 0
    for path, expected in _digest_expectations(out_dir):
        if not os.path.exists(path):
            raise StorageIntegrityError(
                f"artifact file {path} is declared in a digest "
                f"manifest but missing from the payload")
        actual = _file_sha256(path)
        if actual != expected:
            try:
                os.remove(path)
            except OSError:
                logger.exception("could not delete corrupt %s", path)
            raise StorageIntegrityError(
                f"sha256 mismatch for {path}: expected {expected}, "
                f"got {actual}; corrupt file deleted for re-pull")
        checked += 1
    return checked


class Storage:
    """Static download dispatcher, reference storage.py:42 equivalent."""

    @staticmethod
    def download(uri: str, out_dir: Optional[str] = None) -> str:
        logger.info("Copying contents of %s to local", uri)
        if uri.startswith(_MMS_PREFIX):
            # Multi-model passthrough: artifacts are pulled per-TrainedModel
            # by the agent (reference storage.py:69-72).
            return uri
        is_local = uri.startswith(_LOCAL_PREFIX) or os.path.exists(uri)
        if out_dir is None:
            if is_local:
                return Storage._download_local(uri, None)
            out_dir = tempfile.mkdtemp()
        os.makedirs(out_dir, exist_ok=True)

        marker = _success_marker(uri, out_dir)
        if os.path.exists(marker) and not is_local:
            logger.info("Found %s, skipping download of %s", marker, uri)
            return out_dir

        if uri.startswith(_PVC_PREFIX):
            return Storage._download_local(
                "file:///" + uri[len(_PVC_PREFIX):], out_dir)
        if is_local:
            return Storage._download_local(uri, out_dir)

        # Remote pulls go through the retry policy: transient transport
        # errors (and the `storage.download` fault site) replay with
        # backoff — safe because the marker is only written after a
        # full success, so a half-pulled attempt just re-pulls.
        # Terminal errors (unknown scheme, missing SDK, HTTP 4xx) are
        # not connection-level and fail fast.
        from kfserving_tpu.reliability import (
            RetryPolicy,
            fault_sites,
            faults,
        )

        def pull():
            faults.inject_sync(fault_sites.STORAGE_DOWNLOAD, key=uri)
            if uri.startswith(_GCS_PREFIX):
                Storage._download_gcs(uri, out_dir)
            elif uri.startswith(_S3_PREFIX):
                Storage._download_s3(uri, out_dir)
            elif re.search(_AZURE_BLOB_RE, uri):
                Storage._download_azure(uri, out_dir)
            elif uri.startswith(_HTTP_PREFIX):
                Storage._download_from_uri(uri, out_dir)
            else:
                raise Exception(
                    "Cannot recognize storage type for " + uri +
                    "\n'%s', '%s', '%s', and '%s' are the current "
                    "available storage type." % (
                        _GCS_PREFIX, _S3_PREFIX, _LOCAL_PREFIX,
                        "https://"))
            # Inside the retried pull, BEFORE the marker: a payload
            # failing its shipped digests deletes the corrupt file and
            # replays the download — never trusted forever by a
            # URI-keyed marker.
            verify_integrity(out_dir)

        RetryPolicy.from_env("KFS_STORAGE").call(pull)
        with open(marker, "w") as f:
            f.write(uri)
        logger.info("Successfully copied %s to %s", uri, out_dir)
        return out_dir

    # -- local -------------------------------------------------------------
    @staticmethod
    def _download_local(uri: str, out_dir: Optional[str]) -> str:
        """Symlink local artifacts into out_dir (reference storage.py:206-225)."""
        local_path = uri[len(_LOCAL_PREFIX):] if uri.startswith(_LOCAL_PREFIX) else uri
        if not os.path.exists(local_path):
            raise RuntimeError("Local path %s does not exist." % uri)
        if out_dir is None:
            return local_path
        if os.path.isdir(local_path):
            local_path = os.path.join(local_path, "*")
        matched = glob.glob(local_path)
        if not matched:
            raise RuntimeError("Local path %s does not exist." % uri)
        for src in matched:
            _, tail = os.path.split(src)
            dest_path = os.path.join(out_dir, tail)
            if src != dest_path and not os.path.exists(dest_path):
                os.symlink(src, dest_path)
        return out_dir

    # -- http --------------------------------------------------------------
    @staticmethod
    def _download_from_uri(uri: str, out_dir: str) -> str:
        """HTTP(S) download with archive extraction (reference storage.py:227-271)."""
        parsed = urlparse(uri)
        filename = os.path.basename(parsed.path)
        if not filename:
            raise ValueError("No filename contained in URI: %s" % uri)
        mimetype, encoding = _guess_type(filename)
        local_path = os.path.join(out_dir, filename)
        # Per-host credential headers (https secrets; reference
        # pkg/credentials/https/https_secret.go).
        from kfserving_tpu.storage.credentials import https_headers_for

        cred_headers = https_headers_for(uri)
        headers = {"User-Agent": "kfserving-tpu/0.1"}
        headers.update(cred_headers)
        req = UrlRequest(uri, headers=headers)
        if cred_headers:
            # Guarded opener: strips the injected auth on cross-host
            # redirects.  Without credentials, urlopen's default
            # redirect handling is fine (nothing to leak).
            opener = _build_opener_with_safe_redirects(set(cred_headers))
            response_cm = opener.open(req)
        else:
            response_cm = urlopen(req)
        with response_cm as response:
            if response.status != 200:
                raise RuntimeError(
                    "URI: %s returned a %s response code." % (uri, response.status))
            if encoding == "gzip" and mimetype != "application/x-tar":
                # plain .gz file: decompress to the stem name
                stem = filename[:-3]
                with open(os.path.join(out_dir, stem), "wb") as out:
                    shutil.copyfileobj(gzip.GzipFile(fileobj=response), out)
                return out_dir
            with open(local_path, "wb") as out:
                shutil.copyfileobj(response, out)
        if mimetype == "application/zip":
            with zipfile.ZipFile(local_path, "r") as zf:
                zf.extractall(out_dir)
            os.remove(local_path)
        elif mimetype == "application/x-tar":
            with tarfile.open(local_path, "r") as tf:
                # "data" filter: refuse absolute paths / traversal /
                # device nodes in model archives.
                tf.extractall(out_dir, filter="data")
            os.remove(local_path)
        return out_dir

    # -- cloud providers (optional SDKs) ------------------------------------
    @staticmethod
    def _download_gcs(uri: str, out_dir: str) -> None:
        try:
            from google.auth import exceptions
            from google.cloud import storage as gcs
        except ImportError:
            raise RuntimeError(
                "google-cloud-storage is not installed; cannot download %s" % uri)
        try:
            client = gcs.Client()
        except exceptions.DefaultCredentialsError:
            client = gcs.Client.create_anonymous_client()
        bucket_name, _, prefix = uri[len(_GCS_PREFIX):].partition("/")
        bucket = client.bucket(bucket_name, user_project=None)
        for blob in bucket.list_blobs(prefix=prefix):
            name = blob.name.replace(prefix, "", 1).lstrip("/")
            if not name:
                name = os.path.basename(prefix)
            dest = os.path.join(out_dir, name)
            os.makedirs(os.path.dirname(dest) or out_dir, exist_ok=True)
            if not blob.name.endswith("/"):
                blob.download_to_filename(dest)

    @staticmethod
    def _download_s3(uri: str, out_dir: str) -> None:
        """S3 via Minio client configured from env, reference storage.py:82-101,
        273-282 (S3_ENDPOINT/AWS_* variables)."""
        try:
            from minio import Minio
        except ImportError:
            raise RuntimeError("minio is not installed; cannot download %s" % uri)
        endpoint = os.getenv("AWS_ENDPOINT_URL",
                             os.getenv("S3_ENDPOINT", "s3.amazonaws.com"))
        # Accept 1/0/true/false in any case (reference storage.py compares
        # against "0"; k8s users commonly set "False").
        use_ssl = os.getenv("S3_USE_HTTPS", "true").strip().lower() not in (
            "0", "false", "no")
        verify_ssl = os.getenv("S3_VERIFY_SSL", "1").strip().lower() not \
            in ("0", "false", "no")
        http_client = None
        if use_ssl and not verify_ssl:
            # Honor the s3-verifyssl annotation (self-signed endpoints).
            import urllib3

            http_client = urllib3.PoolManager(cert_reqs="CERT_NONE")
        endpoint = re.sub(r"^https?://", "", endpoint)
        client = Minio(endpoint,
                       access_key=os.getenv("AWS_ACCESS_KEY_ID", ""),
                       secret_key=os.getenv("AWS_SECRET_ACCESS_KEY", ""),
                       region=os.getenv("AWS_REGION", ""),
                       secure=use_ssl,
                       http_client=http_client)
        bucket_name, _, prefix = uri[len(_S3_PREFIX):].partition("/")
        for obj in client.list_objects(bucket_name, prefix=prefix,
                                       recursive=True):
            name = obj.object_name.replace(prefix, "", 1).lstrip("/")
            dest = os.path.join(out_dir, name or os.path.basename(prefix))
            os.makedirs(os.path.dirname(dest) or out_dir, exist_ok=True)
            client.fget_object(bucket_name, obj.object_name, dest)

    @staticmethod
    def _download_azure(uri: str, out_dir: str) -> None:
        try:
            from azure.storage.blob import BlobServiceClient
        except ImportError:
            raise RuntimeError(
                "azure-storage-blob is not installed; cannot download %s" % uri)
        match = re.search(_AZURE_BLOB_RE, uri)
        account_url = f"https://{match.group(1)}.blob.core.windows.net"
        container, _, prefix = match.group(2).partition("/")
        client = BlobServiceClient(account_url)
        container_client = client.get_container_client(container)
        for blob in container_client.list_blobs(name_starts_with=prefix):
            name = blob.name.replace(prefix, "", 1).lstrip("/")
            dest = os.path.join(out_dir, name or os.path.basename(prefix))
            os.makedirs(os.path.dirname(dest) or out_dir, exist_ok=True)
            with open(dest, "wb") as f:
                f.write(container_client.download_blob(blob.name).readall())


def _build_opener_with_safe_redirects(credential_keys):
    """urlopen forwards request headers across redirects, which would
    leak a host's Authorization header to whatever host a 302 points at
    (pre-signed CDN URLs are common for artifacts).  This opener strips
    the injected credential headers on cross-host hops and re-evaluates
    the https secrets for the new host."""
    from urllib.request import HTTPRedirectHandler, build_opener

    from kfserving_tpu.storage.credentials import https_headers_for

    class SafeRedirectHandler(HTTPRedirectHandler):
        def redirect_request(self, req, fp, code, msg, headers, newurl):
            new = super().redirect_request(
                req, fp, code, msg, headers, newurl)
            if new is None:
                return None
            old_host = urlparse(req.full_url).hostname
            new_host = urlparse(newurl).hostname
            if old_host != new_host:
                for key in credential_keys:
                    new.remove_header(key.capitalize())
                    new.remove_header(key)
                for key, value in https_headers_for(newurl).items():
                    new.add_header(key, value)
            return new

    return build_opener(SafeRedirectHandler())


def _guess_type(filename: str):
    if filename.endswith(".tar.gz") or filename.endswith(".tgz"):
        return "application/x-tar", "gzip"
    if filename.endswith(".tar"):
        return "application/x-tar", None
    if filename.endswith(".zip"):
        return "application/zip", None
    if filename.endswith(".gz"):
        return None, "gzip"
    return None, None
