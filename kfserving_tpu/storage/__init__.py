from kfserving_tpu.storage.storage import Storage

__all__ = ["Storage"]
