"""Tunnel weather sampler: append one probe record to WEATHER.jsonl.

PARITY.md's honest-ranges story rests on the tunnel epoch distribution
(healthy ~87-110ms RTT / 50-62 MB/s vs degraded ~470ms / 26 MB/s);
round 3 approximated it from ad-hoc repeats.  This samples it
explicitly: run `python -m benchmarks.weather` at intervals (cron,
loops between bench phases) and the record accumulates
(timestamp, rtt_ms, h2d_mb_s, epoch).

The probe is bench.probe_tunnel(): scalar-fetch RTT (block_until_ready
is only a dispatch ack on this transport) + a 19MB device_put.
"""

import json
import os
import sys
import time


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import probe_tunnel

    rec = {"t": round(time.time(), 1),
           "iso": time.strftime("%Y-%m-%dT%H:%M:%S")}
    rec.update(probe_tunnel())
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "WEATHER.jsonl")
    with open(out, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
