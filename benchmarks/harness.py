"""Load generation + stats for the benchmark matrix.

Two modes, mirroring the reference's methodology (reference
test/benchmark/README.md:58-66 tables are vegeta fixed-rate attacks):

- open_loop: fixed arrival rate (requests fire on schedule whether or
  not earlier ones returned) — reproduces the BASELINE.md table shape
  with mean/p50/p95/p99 + success rate at each QPS step.
- closed_loop: bounded concurrency, back-to-back — measures the
  stack's max sustainable throughput (the req/s/chip headline).

Everything drives real HTTP against a live server socket, so JSON
parse (tensorjson), the asyncio server, batcher, and engine are all in
the measured path — VERDICT r1 #2/#4.
"""

import asyncio
import math
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def percentile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_values:
        return float("nan")
    idx = min(len(sorted_values) - 1,
              max(0, math.ceil(q * len(sorted_values)) - 1))
    return sorted_values[idx]


def summarize(latencies_ms: List[float], wall_s: float,
              errors: int = 0,
              first_error: Optional[str] = None,
              shed: int = 0,
              shed_retriable: int = 0) -> Dict[str, Any]:
    """shed: admission-gate 503s — load management, reported apart
    from errors so goodput-vs-shed is visible.  shed_retriable: the
    subset carrying an explicit machine-readable retry signal
    (`"retriable": true` + Retry-After — the brownout gate's shape)."""
    lat = sorted(latencies_ms)
    n = len(lat)
    total = n + errors + shed
    out = {
        "requests": total,
        "errors": errors,
        "success_rate": n / total if total else 0.0,
        "req_per_s": n / wall_s if wall_s > 0 else 0.0,
        "mean_ms": round(statistics.fmean(lat), 3) if lat else None,
        "p50_ms": round(percentile(lat, 0.50), 3) if lat else None,
        "p95_ms": round(percentile(lat, 0.95), 3) if lat else None,
        "p99_ms": round(percentile(lat, 0.99), 3) if lat else None,
    }
    if shed:
        out["shed"] = shed
        out["shed_rate"] = shed / total
    if shed_retriable:
        out["shed_retriable"] = shed_retriable
    if first_error:
        # A failing config must say WHY in the results JSON — an
        # all-errors run once shipped as silent zeros.
        out["first_error"] = first_error[:500]
    return out


def aggregate_rounds(rounds: List[Dict[str, Any]],
                     keys: tuple = ("req_per_s", "p50_ms", "p99_ms")
                     ) -> Dict[str, Any]:
    """Median-of-rounds aggregation for interleaved A/B benches: rounds
    whose percentiles are None (all-error) are excluded from medians but
    their errors/first_error still surface."""
    good = [r for r in rounds if r.get("p99_ms") is not None]
    agg: Dict[str, Any] = {
        "req_per_s_rounds": [round(r.get("req_per_s", 0.0), 2)
                             for r in rounds],
        "shed": sum(r.get("shed", 0) for r in rounds),
        "shed_retriable": sum(r.get("shed_retriable", 0)
                              for r in rounds),
        "errors": sum(r.get("errors", 0) for r in rounds),
    }
    for key in keys:
        agg[f"{key}_median"] = round(statistics.median(
            r[key] for r in good), 2) if good else None
    firsts = [r["first_error"] for r in rounds if r.get("first_error")]
    if firsts:
        agg["first_error"] = firsts[0]
    total = sum(r.get("requests", 0) for r in rounds)
    if total:
        agg["shed_rate"] = round(agg["shed"] / total, 4)
    return agg


async def closed_loop(port: int, path: str, body: bytes,
                      num_requests: int, concurrency: int,
                      host: str = "127.0.0.1",
                      headers: Optional[Dict[str, str]] = None
                      ) -> Dict[str, Any]:
    import aiohttp

    latencies: List[float] = []
    errors = 0
    shed = 0
    shed_retriable = 0
    first_error: Optional[str] = None
    sem = asyncio.Semaphore(concurrency)
    url = f"http://{host}:{port}{path}"
    connector = aiohttp.TCPConnector(limit=concurrency)
    async with aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(total=120)) as session:

        async def one():
            nonlocal errors, shed, shed_retriable, first_error
            async with sem:
                t0 = time.perf_counter()
                try:
                    async with session.post(
                            url, data=body, headers=headers) as resp:
                        payload = await resp.read()
                        if resp.status == 503 and \
                                b'"retriable": true' in payload:
                            # Brownout-gate shedding: explicit
                            # retriable signal + Retry-After.
                            shed += 1
                            shed_retriable += 1
                            return
                        if resp.status == 503 and \
                                b"concurrency limit" in payload:
                            # Admission-gate shedding (server/app.py
                            # "concurrency limit exceeded") is load
                            # management, not failure: count it apart so
                            # goodput-vs-shed is visible (reference
                            # queue-proxy analysis, README.md:131-135).
                            # Other 503s (no replicas / upstream down)
                            # stay errors with first_error set.
                            shed += 1
                            return
                        if resp.status != 200:
                            errors += 1
                            if first_error is None:
                                first_error = (f"HTTP {resp.status}: "
                                               f"{payload[:300]!r}")
                            return
                except Exception as exc:
                    errors += 1
                    if first_error is None:
                        first_error = f"{type(exc).__name__}: {exc}"
                    return
                latencies.append((time.perf_counter() - t0) * 1000.0)

        t0 = time.perf_counter()
        await asyncio.gather(*[one() for _ in range(num_requests)])
        wall = time.perf_counter() - t0
    return summarize(latencies, wall, errors, first_error, shed=shed,
                     shed_retriable=shed_retriable)


async def open_loop(port: int, path: str,
                    body_fn: Callable[[int], bytes],
                    rate_qps: float, duration_s: float,
                    host: str = "127.0.0.1",
                    headers: Optional[Dict[str, str]] = None,
                    label_fn: Optional[Callable[[int], str]] = None,
                    headers_fn: Optional[
                        Callable[[int], Optional[Dict[str, str]]]]
                    = None) -> Dict[str, Any]:
    """Vegeta-style fixed-rate attack: request i fires at t0 + i/rate
    regardless of outstanding requests (open loop — queueing shows up
    as latency, exactly like the reference tables).

    label_fn classifies request i (e.g. by sequence-length class) so
    mixed-traffic runs report per-class latency in out["by_label"];
    headers_fn supplies per-request headers (e.g. a priority-tier
    mix for the brownout bench), overriding `headers`."""
    import aiohttp

    latencies: List[float] = []
    by_label: Dict[str, List[float]] = {}
    shed_by_label: Dict[str, int] = {}
    errors = 0
    shed = 0
    shed_retriable = 0
    first_error: Optional[str] = None
    total = max(1, int(rate_qps * duration_s))
    url = f"http://{host}:{port}{path}"
    connector = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(total=120)) as session:

        async def one(i: int):
            nonlocal errors, shed, shed_retriable, first_error
            hdrs = headers_fn(i) if headers_fn is not None else headers
            t0 = time.perf_counter()
            try:
                async with session.post(
                        url, data=body_fn(i), headers=hdrs) as resp:
                    payload = await resp.read()
                    if resp.status == 503 and \
                            (b"concurrency limit" in payload
                             or b'"retriable": true' in payload):
                        # Load management, not failure: the replica
                        # admission gate (see closed_loop) or the
                        # router's brownout gate (explicit retriable
                        # signal + Retry-After).
                        shed += 1
                        if b'"retriable": true' in payload:
                            shed_retriable += 1
                        if label_fn is not None:
                            lbl = label_fn(i)
                            shed_by_label[lbl] = \
                                shed_by_label.get(lbl, 0) + 1
                        return
                    if resp.status != 200:
                        errors += 1
                        if first_error is None:
                            first_error = (f"HTTP {resp.status}: "
                                           f"{payload[:300]!r}")
                        return
            except Exception as exc:
                errors += 1
                if first_error is None:
                    first_error = f"{type(exc).__name__}: {exc}"
                return
            dt = (time.perf_counter() - t0) * 1000.0
            latencies.append(dt)
            if label_fn is not None:
                by_label.setdefault(label_fn(i), []).append(dt)

        start = time.perf_counter()
        tasks = []
        for i in range(total):
            target = start + i / rate_qps
            delay = target - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(one(i)))
        await asyncio.gather(*tasks)
        wall = time.perf_counter() - start
    out = summarize(latencies, wall, errors, first_error, shed=shed,
                    shed_retriable=shed_retriable)
    out["rate_qps"] = rate_qps
    if shed_by_label:
        out["shed_by_label"] = dict(sorted(shed_by_label.items()))
    if by_label:
        out["by_label"] = {
            label: {
                "requests": len(vals),
                "mean_ms": round(statistics.fmean(vals), 3),
                "p50_ms": round(percentile(sorted(vals), 0.50), 3),
                "p99_ms": round(percentile(sorted(vals), 0.99), 3),
            }
            for label, vals in sorted(by_label.items())}
    return out


def np_json_body(key: str, arr: np.ndarray) -> bytes:
    """Dense V1 body the tensorjson fast path parses."""
    import json

    return json.dumps({key: arr.tolist()}).encode()


async def pipelined_closed_loop(port: int, path: str, body: bytes,
                                num_requests: int, connections: int = 4,
                                headers: Optional[Dict[str, str]] = None,
                                host: str = "127.0.0.1") -> Dict[str, Any]:
    """Max-throughput mode: raw sockets, HTTP/1.1 pipelining (the server
    supports it), minimal client-side work.  The aiohttp client costs
    ~1ms/request of the single shared host core; this mode measures what
    the *server* can actually sustain.  Latency is not reported —
    pipelined requests queue by design."""
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    request = (f"POST {path} HTTP/1.1\r\nHost: {host}\r\n"
               f"Content-Length: {len(body)}\r\n{extra}\r\n"
               ).encode() + body
    per_conn = num_requests // connections

    async def one_connection():
        reader, writer = await asyncio.open_connection(host, port)
        ok = 0
        try:
            async def pump():
                n = 0
                while n < per_conn:
                    k = min(8, per_conn - n)
                    writer.write(request * k)
                    await writer.drain()
                    n += k

            write_task = asyncio.ensure_future(pump())
            for _ in range(per_conn):
                status = await reader.readline()
                if b"200" in status:
                    ok += 1
                length = 0
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b""):
                        break
                    if line.lower().startswith(b"content-length:"):
                        length = int(line.split(b":")[1])
                await reader.readexactly(length)
            await write_task
            return ok, per_conn
        finally:
            writer.close()

    t0 = time.perf_counter()
    results = await asyncio.gather(
        *[one_connection() for _ in range(connections)])
    wall = time.perf_counter() - t0
    ok = sum(r[0] for r in results)
    total = sum(r[1] for r in results)
    return {"requests": total, "errors": total - ok,
            "success_rate": ok / total if total else 0.0,
            "req_per_s": ok / wall if wall > 0 else 0.0,
            "connections": connections, "pipelined": True}
