"""Sustained-load soak with process recycling (VERDICT r2 weak #5).

Round 2 measured the tunneled device transport leaking ~3.2 GB/min RSS
under 60 QPS of binary-wire ResNet and *claimed* orchestrator-level
process recycling as the mitigation without building it.  This drives
the full claimed stack end-to-end:

  load gen -> IngressRouter -> subprocess replica (owns the TPU) ->
  RecyclePolicy watchdog -> warm-standby swap (spawn -> mmap-param
  activate while the incumbent serves -> drain) -> router announced-
  swap holds carry any residual gap.

ISSUE 10 made the warm standby the DEFAULT lifecycle: the successor
activates off the mmap param cache while the incumbent still serves,
so the swap window is 0 by construction; `--exclusive` measures the
exclusive-device ordering (drain -> activate inside an announced
window the router bridges by holding, not shedding).

Success = RSS stays bounded by the policy across >=1 recycle, client
sees no failed requests, and the committed swap_breakdown shows where
every swap's milliseconds went (standby_spawn / activate / drain, plus
the successor's own boot marks — params_mmap on a cache hit).

Usage: python -m benchmarks.soak [--minutes 6] [--qps 60]
       [--max-rss-mb 4096] [--exclusive] [--smoke]
Writes SOAK.json.
"""

import argparse
import asyncio
import json
import os
import tempfile
import time


def _registry_series(substr: str) -> dict:
    """Samples of every registry series whose name contains `substr`
    (the soak runs router + orchestrator in-process, so their counters
    are readable without a scrape)."""
    from kfserving_tpu.observability import REGISTRY

    out = {}
    for line in REGISTRY.render_lines():
        if line.startswith("#") or substr not in line:
            continue
        try:
            series, value = line.rsplit(" ", 1)
            out[series] = float(value)
        except ValueError:
            continue
    return out


async def run_soak(minutes: float, qps: float, max_rss_mb: float,
                   smoke: bool, max_requests: int = None,
                   buffer_deadline_s: float = 15.0,
                   exclusive: bool = False) -> dict:
    import aiohttp
    import numpy as np

    from kfserving_tpu.control.controller import Controller
    from kfserving_tpu.control.router import IngressRouter
    from kfserving_tpu.control.spec import InferenceService, PredictorSpec
    from kfserving_tpu.control.subprocess_orchestrator import (
        RecyclePolicy,
        SubprocessOrchestrator,
        _proc_rss_mb,
    )
    from kfserving_tpu.protocol import v2 as v2proto

    model_dir = tempfile.mkdtemp(prefix="soak-")
    if smoke:
        cfg = {"architecture": "mlp",
               "arch_kwargs": {"input_dim": 64, "features": [128],
                               "num_classes": 10},
               "max_batch_size": 16, "max_latency_ms": 5.0,
               "warmup": True, "output": "argmax"}
        image = np.random.default_rng(0).normal(size=(1, 64)) \
            .astype(np.float32)
    else:
        cfg = {"architecture": "resnet50", "max_batch_size": 128,
               "batch_buckets": [16, 32, 64, 128], "pipeline_depth": 3,
               "max_latency_ms": 15.0, "warmup": True,
               "input_dtype": "uint8", "scale": 1.0 / 255.0,
               "output": "argmax"}
        image = np.random.default_rng(0).integers(
            0, 256, size=(1, 224, 224, 3)).astype(np.uint8)
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    body, hlen = v2proto.make_binary_request({"input_0": image})

    env = {"JAX_PLATFORMS": "cpu"} if smoke else {}
    orch = SubprocessOrchestrator(
        env_overrides=env,
        recycle=RecyclePolicy(max_rss_mb=max_rss_mb,
                              max_requests=max_requests,
                              check_interval_s=2.0 if smoke else 5.0,
                              exclusive_device=exclusive,
                              min_age_s=10.0 if smoke else 30.0))
    controller = Controller(orch)
    router = IngressRouter(controller, upstream_timeout_s=180.0,
                           buffer_deadline_s=buffer_deadline_s)
    await router.start_async()
    results = {"ok": 0, "fail": 0, "statuses": {}}
    rss_samples = []
    lat = []

    async def one(session, sem):
        async with sem:
            t0 = time.perf_counter()
            try:
                async with session.post(
                        f"http://127.0.0.1:{router.http_port}"
                        "/v2/models/soak/infer", data=body,
                        headers={"Inference-Header-Content-Length":
                                 str(hlen)}) as resp:
                    await resp.read()
                    st = resp.status
            except Exception as e:
                st = f"exc:{type(e).__name__}"
            lat.append((time.perf_counter() - t0) * 1e3)
            key = str(st)
            results["statuses"][key] = results["statuses"].get(key, 0) + 1
            if st == 200:
                results["ok"] += 1
            else:
                results["fail"] += 1

    async def sampler():
        while True:
            await asyncio.sleep(5.0)
            reps = orch.replicas("default/soak/predictor")
            if reps and reps[0].handle:
                # kfslint: disable=async-blocking — /proc reads are
                # RAM-backed (same waiver as the recycle watchdog's).
                rss = _proc_rss_mb(reps[0].handle.process.pid)
                if rss is not None:
                    rss_samples.append(
                        {"t": round(time.perf_counter() - t_start, 1),
                         "rss_mb": round(rss, 0),
                         "recycles": orch.recycle_count})

    try:
        isvc = InferenceService(
            name="soak",
            predictor=PredictorSpec(framework="jax",
                                    storage_uri=f"file://{model_dir}"))
        await controller.apply(isvc)
        t_start = time.perf_counter()
        samp = asyncio.ensure_future(sampler())
        interval = 1.0 / qps
        deadline = t_start + minutes * 60.0
        tasks = []
        # Bounded client concurrency: during a swap window requests
        # buffer in the router; without a cap the open loop would pile
        # thousands of sockets.
        sem = asyncio.Semaphore(256)
        timeout = aiohttp.ClientTimeout(total=180.0)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            i = 0
            while time.perf_counter() < deadline:
                tasks.append(asyncio.ensure_future(one(session, sem)))
                i += 1
                next_t = t_start + i * interval
                delay = next_t - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
            await asyncio.gather(*tasks)
        samp.cancel()
        lat.sort()
        from benchmarks.harness import percentile

        windows_ms = sorted(w * 1000.0 for w in orch.swap_windows_s)
        return {
            "minutes": minutes, "qps": qps, "max_rss_mb": max_rss_mb,
            "max_requests": max_requests,
            "buffer_deadline_s": buffer_deadline_s,
            "mode": "exclusive_standby" if exclusive
                    else "warm_standby",
            "requests": results["ok"] + results["fail"],
            "ok": results["ok"], "fail": results["fail"],
            "statuses": results["statuses"],
            "recycles": orch.recycle_count,
            "promotions": orch.promotions,
            "swap_failures": orch.swap_failures,
            # Unavailability gap per swap: warm-standby swaps are 0 by
            # construction (successor entered rotation before the
            # incumbent drained); the exclusive mode measures
            # chip-release -> successor-serving.
            "swap_windows_s": list(orch.swap_windows_s),
            "swap_window_p99_ms": (round(percentile(windows_ms, 0.99),
                                         1) if windows_ms else None),
            "swap_breakdown": list(orch.swap_breakdown),
            # Announced-swap holds the router absorbed instead of
            # shedding (and the param-cache outcomes of every replica
            # boot this run spawned, scraped from the successors).
            "router_swap_holds": _registry_series(
                "router_swap_held_total"),
            "p50_ms": round(percentile(lat, 0.5), 1) if lat else None,
            "p99_ms": round(percentile(lat, 0.99), 1) if lat else None,
            "max_ms": round(lat[-1], 1) if lat else None,
            "rss_timeline": rss_samples,
            "rss_peak_mb": max((s["rss_mb"] for s in rss_samples),
                               default=None),
        }
    finally:
        await router.stop_async()
        await orch.shutdown()


def main():
    import logging
    import sys

    # Recycle decisions and swap windows are INFO-level; the soak's
    # record must show them (a silent watchdog is indistinguishable
    # from a healthy no-trigger run otherwise).
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=6.0)
    ap.add_argument("--qps", type=float, default=60.0)
    ap.add_argument("--max-rss-mb", type=float, default=4096.0)
    ap.add_argument("--max-requests", type=int, default=None,
                    help="recycle every N served requests (deterministic "
                         ">=2 swaps per soak)")
    ap.add_argument("--buffer-deadline-s", type=float, default=15.0)
    ap.add_argument("--exclusive", action="store_true",
                    help="exclusive-device ordering: drain -> activate "
                         "inside an announced window the router holds "
                         "across (default: warm standby — activate "
                         "BEFORE drain, zero-gap)")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    out = asyncio.run(run_soak(args.minutes, args.qps, args.max_rss_mb,
                               args.smoke, args.max_requests,
                               args.buffer_deadline_s,
                               exclusive=args.exclusive))
    with open("SOAK.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    # Direct-script invocation (`python benchmarks/soak.py`) puts
    # benchmarks/ itself on sys.path, breaking the in-function
    # `from benchmarks.harness import ...` — add the repo root so both
    # that and `python -m benchmarks.soak` work.
    import sys

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    main()
