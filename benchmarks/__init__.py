"""Benchmark harness for the TPU serving stack (reference
test/benchmark equivalent: vegeta-style fixed-rate attacks + the §6
latency tables, driven through the real HTTP data plane)."""
