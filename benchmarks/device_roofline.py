"""Device-only model step timing: what the chip does with the tunnel
taken out of the loop.

The round-2 engine stats measure dispatch->host-visible-result, which on
this bench host includes an ~87 ms runtime round trip per batch — a
floor on wall MFU but not a statement about the silicon.  This tool
measures the flagship models the way the attention kernels were
measured (ROOFLINE.md "Flash attention" row): K model steps chained
inside one on-device ``lax.fori_loop`` with an explicit data dependency
between iterations, timed at K=1 and K=N.  The per-step device time is

    (t_N - t_1) / (N - 1)

which cancels dispatch, transfer, and the single sync.

The chain dependency is a zero-scaled scalar folded back into the input
(x + 0*mean(logits)): XLA cannot DCE or reorder the steps, and the
added work is one reduction + broadcast per step (noise at these
FLOP counts).

Usage:  python -m benchmarks.device_roofline [--model resnet50|bert]
Prints one JSON line per (model, batch) with ms/step, TF/s, and MFU
against the chip's bf16 peak.
"""

import argparse
import json
import time

import numpy as np


def _flops_of(jitted, params, x) -> float:
    """XLA cost-model FLOPs for one step (same source the engine stats
    use, engine/jax_engine.py:303-321)."""
    lowered = jitted.lower(params, x)
    analysis = lowered.cost_analysis()
    if not analysis:
        analysis = lowered.compile().cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return float((analysis or {}).get("flops", 0.0))


def chained_step_time(apply_fn, params, x, n: int = 12,
                      reps: int = 3) -> dict:
    """Median of `reps` (t_n - t_1)/(n-1) measurements, seconds/step."""
    import jax
    import jax.numpy as jnp

    def chain(k):
        def body(_, carry):
            out = apply_fn(params, carry)
            leaves = jax.tree.leaves(out)
            dep = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            zero = (dep * 0.0)
            if isinstance(carry, dict):
                return {key: (v + zero.astype(v.dtype)
                              if jnp.issubdtype(v.dtype, jnp.floating)
                              else v + zero.astype(jnp.int32).astype(v.dtype))
                        for key, v in carry.items()}
            if jnp.issubdtype(carry.dtype, jnp.floating):
                return carry + zero.astype(carry.dtype)
            return carry + zero.astype(jnp.int32).astype(carry.dtype)

        return jax.jit(lambda p, v: jax.lax.fori_loop(0, k, body, v),
                       static_argnums=())

    f1 = chain(1)
    fn = chain(n)
    # compile both
    jax.block_until_ready(f1(params, x))
    jax.block_until_ready(fn(params, x))
    per_step = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f1(params, x))
        t1 = time.perf_counter()
        jax.block_until_ready(fn(params, x))
        t2 = time.perf_counter()
        per_step.append(((t2 - t1) - (t1 - t0)) / (n - 1))
    per_step.sort()
    return {"sec_per_step": per_step[len(per_step) // 2],
            "t1_sec": t1 - t0, "n": n}


def measure(model_name: str, batches, seq=None) -> list:
    import jax

    from kfserving_tpu.engine.jax_engine import device_peak_flops
    from kfserving_tpu.models import registry

    if model_name == "resnet50":
        spec = registry.create_model("resnet50")
        make_x = lambda b: np.random.default_rng(0).normal(
            size=(b, 224, 224, 3)).astype(np.float32)
    elif model_name == "bert":
        spec = registry.create_model("bert")
        make_x = lambda b: np.random.default_rng(0).integers(
            1, 1000, size=(b, seq or 128)).astype(np.int32)
    else:
        raise SystemExit(f"unknown model {model_name}")
    params = registry.init_params(spec)
    apply_fn = registry.apply_fn_for(spec)
    jitted = jax.jit(apply_fn)
    peak = device_peak_flops()
    rows = []
    for b in batches:
        x = jax.device_put(make_x(b))
        flops = _flops_of(jitted, params, x)
        t = chained_step_time(apply_fn, params, x)
        sec = t["sec_per_step"]
        tf_s = flops / sec / 1e12 if sec > 0 else None
        row = {"model": model_name, "batch": b,
               "seq": seq if model_name == "bert" else None,
               "ms_per_step": round(sec * 1e3, 3),
               "ms_per_item": round(sec * 1e3 / b, 4),
               "flops_per_step": flops,
               "tflops_per_s": round(tf_s, 2) if tf_s else None,
               "mfu": round(flops / sec / peak, 4) if peak and sec > 0
               else None,
               "t1_wall_ms": round(t["t1_sec"] * 1e3, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="all",
                    choices=["resnet50", "bert", "all"])
    ap.add_argument("--batches", default="32,64,128,256")
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()
    batches = [int(b) for b in args.batches.split(",")]
    out = []
    if args.model in ("resnet50", "all"):
        out += measure("resnet50", batches)
    if args.model in ("bert", "all"):
        out += measure("bert", batches, seq=args.seq)
    with open("DEVICE_ROOFLINE.json", "w") as f:
        json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
