"""Device-only model step timing: what the chip does with the tunnel
taken out of the loop.

The round-2 engine stats measure dispatch->host-visible-result, which on
this bench host includes an ~87 ms runtime round trip per batch — a
floor on wall MFU but not a statement about the silicon.  This tool
measures the flagship models the way the attention kernels were
measured (ROOFLINE.md "Flash attention" row): K model steps chained
inside one on-device ``lax.fori_loop`` with an explicit data dependency
between iterations, timed at K=1 and K=N.  The per-step device time is

    (t_N - t_1) / (N - 1)

which cancels dispatch, transfer, and the single sync.

The chain dependency is a zero-scaled scalar folded back into the input
(x + 0*mean(logits)): XLA cannot DCE or reorder the steps, and the
added work is one reduction + broadcast per step (noise at these
FLOP counts).

Usage:  python -m benchmarks.device_roofline [--model resnet50|bert]
Prints one JSON line per (model, batch) with ms/step, TF/s, and MFU
against the chip's bf16 peak.
"""

import argparse
import json
import time

import numpy as np


def _flops_of(jitted, params, x) -> float:
    """XLA cost-model FLOPs for one step (same source the engine stats
    use, engine/jax_engine.py:303-321)."""
    lowered = jitted.lower(params, x)
    analysis = lowered.cost_analysis()
    if not analysis:
        analysis = lowered.compile().cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return float((analysis or {}).get("flops", 0.0))


def _chain_dep(out, v):
    """Fold a model output into the next step's input without changing
    its value at runtime and without being eliminable at compile time.

    NOT `0.0 * sum(out)`: for integer inputs the int-cast zero is a
    valid strength reduction and XLA deletes the whole model (measured:
    a "4098 TF/s BERT" = 20x chip peak).  And not plain `sum(out)`
    either: a reduce-sum of a matmul factors through it
    (sum(A@B) = sum_k(sum_i A)_k (sum_j B)_k), which let XLA skip
    BERT's 96-GFLOP vocab projection (measured 105% "MFU").  The
    squared sum consumes every output element irreducibly; scaled by
    1e-30 it is a non-constant float the simplifier cannot prove zero —
    its int cast truncates to 0 and its float add is far below one ulp
    of any activation, both only at runtime."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(out)
    dep = sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
              for l in leaves) * 1e-30

    def inject(a):
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a + dep.astype(a.dtype)
        return a + dep.astype(jnp.int32).astype(a.dtype)

    if isinstance(v, dict):
        return {k: inject(a) for k, a in v.items()}
    return inject(v)


def _fetch_probe(v):
    """Reduce a chain carry to one f32 scalar whose value depends on
    every element — fetching it joins the device timeline at ~zero
    transfer cost regardless of carry size."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(v)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
               for l in leaves)


def dispatch_chained_step_time(apply_fn, params, x, n: int = 24,
                               reps: int = 3) -> dict:
    """Host-chained variant for models whose fori_loop chain exceeds the
    tunnel's remote-compile body limit (BERT-base hits HTTP 413): issue K
    async dispatches where each step's input carries a data dependency
    on the previous output, sync once at the end.  The device executes
    the queue back-to-back, so (t_K - t_1)/(K-1) still cancels the
    single round trip and dispatch tail."""
    import jax

    def step(p, v):
        return _chain_dep(apply_fn(p, v), v)

    jstep = jax.jit(step)
    probe = jax.jit(_fetch_probe)

    def run(k):
        # Sync via a tiny scalar D2H fetch, NOT block_until_ready: on
        # the tunneled backend block_until_ready acks the dispatch
        # without waiting for execution (measured 0.24 ms for a 458
        # GFLOP program); only a fetch truly joins the device timeline.
        v = x
        for _ in range(k):
            v = jstep(params, v)
        np.asarray(probe(v))

    run(2)  # compile + queue warm
    per_step = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run(1)
        t1 = time.perf_counter()
        run(n)
        t2 = time.perf_counter()
        per_step.append(((t2 - t1) - (t1 - t0)) / (n - 1))
    per_step.sort()
    return {"sec_per_step": per_step[len(per_step) // 2],
            "t1_sec": t1 - t0, "n": n, "method": "dispatch-chain"}


def chained_step_time(apply_fn, params, x, n: int = 12,
                      reps: int = 3) -> dict:
    """Median of `reps` (t_n - t_1)/(n-1) measurements, seconds/step."""
    import jax

    def chain(k):
        def body(_, carry):
            return _chain_dep(apply_fn(params, carry), carry)

        # Scalar-probe output: the fetch that times the run transfers 4
        # bytes but depends on every chained step (block_until_ready is
        # a dispatch ack on the tunneled backend, not a join).
        return jax.jit(
            lambda p, v: _fetch_probe(jax.lax.fori_loop(0, k, body, v)))

    f1 = chain(1)
    fn = chain(n)
    # compile both
    np.asarray(f1(params, x))
    np.asarray(fn(params, x))
    per_step = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(f1(params, x))
        t1 = time.perf_counter()
        np.asarray(fn(params, x))
        t2 = time.perf_counter()
        per_step.append(((t2 - t1) - (t1 - t0)) / (n - 1))
    per_step.sort()
    return {"sec_per_step": per_step[len(per_step) // 2],
            "t1_sec": t1 - t0, "n": n, "method": "fori-chain"}


def measure(model_name: str, batches, seq=None, method="auto") -> list:
    import jax

    from kfserving_tpu.engine.jax_engine import device_peak_flops
    from kfserving_tpu.models import registry

    if model_name == "resnet50":
        spec = registry.create_model("resnet50")
        make_x = lambda b: np.random.default_rng(0).normal(
            size=(b, 224, 224, 3)).astype(np.float32)
    elif model_name == "bert":
        spec = registry.create_model("bert")
        make_x = lambda b: np.random.default_rng(0).integers(
            1, 1000, size=(b, seq or 128)).astype(np.int32)
    else:
        raise SystemExit(f"unknown model {model_name}")
    params = registry.init_params(spec)
    apply_fn = registry.apply_fn_for(spec)
    jitted = jax.jit(apply_fn)
    peak = device_peak_flops()
    rows = []
    for b in batches:
        x = jax.device_put(make_x(b))
        flops = _flops_of(jitted, params, x)
        if method == "dispatch":
            t = dispatch_chained_step_time(apply_fn, params, x)
        else:
            try:
                t = chained_step_time(apply_fn, params, x)
            except Exception as exc:  # chain too big for remote compile
                print(f"# fori chain failed ({type(exc).__name__}); "
                      "falling back to dispatch chain", flush=True)
                t = dispatch_chained_step_time(apply_fn, params, x)
        sec = t["sec_per_step"]
        tf_s = flops / sec / 1e12 if sec > 0 else None
        row = {"model": model_name, "batch": b,
               "seq": seq if model_name == "bert" else None,
               "method": t.get("method", "fori-chain"),
               "ms_per_step": round(sec * 1e3, 3),
               "ms_per_item": round(sec * 1e3 / b, 4),
               "flops_per_step": flops,
               "tflops_per_s": round(tf_s, 2) if tf_s else None,
               "mfu": round(flops / sec / peak, 4) if peak and sec > 0
               else None,
               "t1_wall_ms": round(t["t1_sec"] * 1e3, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="all",
                    choices=["resnet50", "bert", "all"])
    ap.add_argument("--batches", default="32,64,128,256")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--method", default="auto",
                    choices=["auto", "dispatch"])
    args = ap.parse_args()
    batches = [int(b) for b in args.batches.split(",")]
    out = []
    if args.model in ("resnet50", "all"):
        out += measure("resnet50", batches, method=args.method)
    if args.model in ("bert", "all"):
        out += measure("bert", batches, seq=args.seq,
                       method=args.method)
    # Merge with prior invocations (partial runs build the table up).
    try:
        with open("DEVICE_ROOFLINE.json") as f:
            prior = json.load(f)
    except Exception:
        prior = []
    key = lambda r: (r["model"], r["batch"], r.get("seq"))
    merged = {key(r): r for r in prior}
    merged.update({key(r): r for r in out})
    with open("DEVICE_ROOFLINE.json", "w") as f:
        json.dump(sorted(merged.values(),
                         key=lambda r: (r["model"], r["batch"])), f, indent=2)


if __name__ == "__main__":
    main()
